"""Schema layer: every wire message, WAL entry, state event, and hash-origin
type in the framework.

This is the TPU-native rebuild's equivalent of the reference's protobuf schema
(reference: mirbftpb/mirbft.proto:1-455).  Same message vocabulary — 15 network
message types (mirbft.proto:193-211), 8 persistent WAL entry types
(mirbft.proto:131-143), 10 state-event input types (mirbft.proto:353-406), 5
hash-origin types (mirbft.proto:408-448) — expressed as Python dataclasses
with the deterministic codec from ``wire``.

Everything above this layer depends on it; it depends on nothing.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import wire
from .wire import BOOL, BYTES, I32, U32, U64, Nested, OneOf, Rep


# ---------------------------------------------------------------------------
# Network state (reference: mirbft.proto:22-115)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class NetworkConfig:
    """Consensus-replicated network configuration (mirbft.proto:23-77)."""

    nodes: list = field(default_factory=list)  # active node IDs; len == N
    checkpoint_interval: int = 0  # sequences between checkpoints
    max_epoch_length: int = 0  # max seqnos preprepared per epoch
    number_of_buckets: int = 0  # partitions of the request space
    f: int = 0  # byzantine faults tolerated, < N/3


@dataclass(slots=True)
class NetworkClient:
    """Per-client window state, reflected in checkpoints (mirbft.proto:79-106)."""

    id: int = 0
    width: int = 0
    width_consumed_last_checkpoint: int = 0
    low_watermark: int = 0  # lowest uncommitted req_no
    committed_mask: bytes = b""  # bitmask of commits above low_watermark


@dataclass(slots=True)
class ReconfigNewClient:
    id: int = 0
    width: int = 0


@dataclass(slots=True)
class ReconfigRemoveClient:
    client_id: int = 0


@dataclass(slots=True)
class Reconfiguration:
    """Oneof: ReconfigNewClient | ReconfigRemoveClient | NetworkConfig
    (mirbft.proto:117-128)."""

    type: object = None


@dataclass(slots=True)
class NetworkState:
    config: NetworkConfig | None = None
    clients: list = field(default_factory=list)  # [NetworkClient]
    pending_reconfigurations: list = field(default_factory=list)
    reconfigured: bool = False


# ---------------------------------------------------------------------------
# Requests and acks (mirbft.proto:229-239)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Request:
    client_id: int = 0
    req_no: int = 0
    data: bytes = b""


@dataclass(slots=True)
class RequestAck:
    client_id: int = 0
    req_no: int = 0
    digest: bytes = b""


# ---------------------------------------------------------------------------
# Epoch configuration (mirbft.proto:309-351)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class EpochConfig:
    number: int = 0
    leaders: list = field(default_factory=list)  # node IDs
    planned_expiration: int = 0  # last seq_no this epoch may preprepare


@dataclass(slots=True)
class Checkpoint:
    seq_no: int = 0
    value: bytes = b""


@dataclass(slots=True)
class NewEpochConfig:
    config: EpochConfig | None = None
    starting_checkpoint: Checkpoint | None = None
    # Digests finalizing in-flight sequences above the starting checkpoint,
    # indexed by seq_no offset; empty digest == null request.
    final_preprepares: list = field(default_factory=list)  # [bytes]


@dataclass(slots=True)
class EpochChangeSetEntry:
    epoch: int = 0
    seq_no: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class EpochChange:
    """PBFT view-change message, slightly adapted to Mir (mirbft.proto:273-293)."""

    new_epoch: int = 0
    checkpoints: list = field(default_factory=list)  # [Checkpoint] — the C-set
    p_set: list = field(default_factory=list)  # [EpochChangeSetEntry]
    q_set: list = field(default_factory=list)  # [EpochChangeSetEntry]


@dataclass(slots=True)
class EpochChangeAck:
    originator: int = 0
    epoch_change: EpochChange | None = None


@dataclass(slots=True)
class RemoteEpochChange:
    node_id: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class NewEpoch:
    """PBFT NewView + Bracha reliable broadcast of the config (mirbft.proto:330-351)."""

    new_config: NewEpochConfig | None = None
    epoch_changes: list = field(default_factory=list)  # [RemoteEpochChange]


# ---------------------------------------------------------------------------
# Normal-case three-phase messages (mirbft.proto:241-266)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Preprepare:
    seq_no: int = 0
    epoch: int = 0
    batch: list = field(default_factory=list)  # [RequestAck]


@dataclass(slots=True)
class Prepare:
    seq_no: int = 0
    epoch: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class Commit:
    seq_no: int = 0
    epoch: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class Suspect:
    epoch: int = 0


# ---------------------------------------------------------------------------
# Fetch / forward (mirbft.proto:213-227)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class FetchBatch:
    seq_no: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class ForwardBatch:
    seq_no: int = 0
    request_acks: list = field(default_factory=list)  # [RequestAck]
    digest: bytes = b""


@dataclass(slots=True)
class FetchRequest:
    """Distinct type for the fetch_request oneof arm (the reference reuses
    RequestAck at mirbft.proto:207; a distinct type keeps step routing
    explicit)."""

    client_id: int = 0
    req_no: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class ForwardRequest:
    request_ack: RequestAck | None = None
    request_data: bytes = b""


@dataclass(slots=True)
class NewEpochEcho:
    """Bracha echo of a NewEpochConfig.  The reference reuses NewEpochConfig
    for both the echo (tag 9) and ready (tag 10) arms of the Msg oneof
    (mirbft.proto:203-204); explicit wrapper types keep step routing
    unambiguous."""

    new_epoch_config: NewEpochConfig | None = None


@dataclass(slots=True)
class NewEpochReady:
    """Bracha ready of a NewEpochConfig (see NewEpochEcho)."""

    new_epoch_config: NewEpochConfig | None = None


@dataclass(slots=True)
class Msg:
    """The wire-message oneof: 15 types (mirbft.proto:193-211)."""

    type: object = None


# ---------------------------------------------------------------------------
# Persistent (WAL) entries (mirbft.proto:131-191)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class QEntry:
    """Persisted before a batch is Preprepared (mirbft.proto:170-177)."""

    seq_no: int = 0
    digest: bytes = b""
    requests: list = field(default_factory=list)  # [RequestAck]


@dataclass(slots=True)
class PEntry:
    """Persisted before a batch is Prepared (mirbft.proto:179-184)."""

    seq_no: int = 0
    digest: bytes = b""


@dataclass(slots=True)
class CEntry:
    """Persisted before a Checkpoint message is sent (mirbft.proto:186-191)."""

    seq_no: int = 0
    checkpoint_value: bytes = b""
    network_state: NetworkState | None = None


@dataclass(slots=True)
class NEntry:
    """New sequence allocation; persisted before log truncation (mirbft.proto:148-152)."""

    seq_no: int = 0
    epoch_config: EpochConfig | None = None


@dataclass(slots=True)
class FEntry:
    """Epoch gracefully ended (mirbft.proto:154-156)."""

    ends_epoch_config: EpochConfig | None = None


@dataclass(slots=True)
class ECEntry:
    """Epoch change sent; truncation halts until the next epoch (mirbft.proto:160-162)."""

    epoch_number: int = 0


@dataclass(slots=True)
class TEntry:
    """State transfer requested (mirbft.proto:164-168)."""

    seq_no: int = 0
    value: bytes = b""


@dataclass(slots=True)
class Persistent:
    """WAL entry oneof: 8 types (mirbft.proto:131-143)."""

    type: object = None


# ---------------------------------------------------------------------------
# Hash results (mirbft.proto:408-448)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class HashOriginRequest:
    source: int = 0
    request: Request | None = None


@dataclass(slots=True)
class HashOriginVerifyRequest:
    source: int = 0
    request_ack: RequestAck | None = None
    request_data: bytes = b""


@dataclass(slots=True)
class HashOriginBatch:
    source: int = 0
    epoch: int = 0
    seq_no: int = 0
    request_acks: list = field(default_factory=list)  # [RequestAck]


@dataclass(slots=True)
class HashOriginVerifyBatch:
    source: int = 0
    seq_no: int = 0
    request_acks: list = field(default_factory=list)  # [RequestAck]
    expected_digest: bytes = b""


@dataclass(slots=True)
class HashOriginEpochChange:
    source: int = 0
    origin: int = 0
    epoch_change: EpochChange | None = None


@dataclass(slots=True)
class HashResult:
    digest: bytes = b""
    type: object = None  # one of the 5 HashOrigin* classes


@dataclass(slots=True)
class CheckpointResult:
    """Consumer-computed checkpoint (mirbft.proto:450-455)."""

    seq_no: int = 0
    value: bytes = b""
    network_state: NetworkState | None = None
    reconfigured: bool = False


# ---------------------------------------------------------------------------
# State events (mirbft.proto:353-406)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class InitialParameters:
    id: int = 0
    batch_size: int = 0
    heartbeat_ticks: int = 0
    suspect_ticks: int = 0
    new_epoch_timeout_ticks: int = 0
    buffer_size: int = 0


@dataclass(slots=True)
class EventInitialize:
    initial_parms: InitialParameters | None = None


@dataclass(slots=True)
class EventLoadEntry:
    index: int = 0
    data: Persistent | None = None


@dataclass(slots=True)
class EventLoadRequest:
    request_ack: RequestAck | None = None


@dataclass(slots=True)
class EventCompleteInitialization:
    pass


@dataclass(slots=True)
class EventActionResults:
    digests: list = field(default_factory=list)  # [HashResult]
    checkpoints: list = field(default_factory=list)  # [CheckpointResult]


@dataclass(slots=True)
class EventTransfer:
    c_entry: CEntry | None = None


@dataclass(slots=True)
class EventPropose:
    request: Request | None = None


@dataclass(slots=True)
class EventProposeBatch:
    """Several local proposals arriving in one delivery.  Semantically
    identical to delivering each request as its own EventPropose in list
    order; the batch form exists so the harness/runtime can coalesce the
    per-request propose fan-out (one event per request per node otherwise
    dominates event counts — at ladder scale ~16 of every 16.5 events were
    single proposes).  The reference proposes individually (reference:
    mirbft.go:61-121); batching is a framework-level ingress feature."""

    requests: list = field(default_factory=list)  # [Request]


@dataclass(slots=True)
class EventStep:
    source: int = 0
    msg: Msg | None = None


@dataclass(slots=True)
class EventStepBatch:
    """One inbound transport frame carrying several messages from the same
    peer.  Semantically identical to delivering each message as its own
    EventStep in list order; the batch form exists so executors can coalesce
    the per-target sends of one Actions batch into one delivery (the n^2
    RequestAck fan-out otherwise dominates event counts at ladder scale).
    The reference delivers messages individually (reference:
    processor.go:95-103); batching is a framework-level transport feature."""

    source: int = 0
    msgs: list = field(default_factory=list)  # [Msg]


@dataclass(slots=True)
class EventTick:
    pass


@dataclass(slots=True)
class EventActionsReceived:
    pass


@dataclass(slots=True)
class StateEvent:
    """The state-machine input oneof: 10 types (mirbft.proto:394-405)."""

    type: object = None


# ---------------------------------------------------------------------------
# Specs (encoding order == declaration order)
# ---------------------------------------------------------------------------

NetworkConfig._spec_ = (
    ("nodes", Rep(U64)),
    ("checkpoint_interval", I32),
    ("max_epoch_length", U64),
    ("number_of_buckets", I32),
    ("f", I32),
)
NetworkClient._spec_ = (
    ("id", U64),
    ("width", U32),
    ("width_consumed_last_checkpoint", U32),
    ("low_watermark", U64),
    ("committed_mask", BYTES),
)
ReconfigNewClient._spec_ = (("id", U64), ("width", U32))
ReconfigRemoveClient._spec_ = (("client_id", U64),)
Reconfiguration._spec_ = (
    (
        "type",
        OneOf(
            (1, ReconfigNewClient),
            (2, ReconfigRemoveClient),
            (3, NetworkConfig),
            allow_unset=False,
        ),
    ),
)
NetworkState._spec_ = (
    ("config", Nested(NetworkConfig)),
    ("clients", Rep(Nested(NetworkClient))),
    ("pending_reconfigurations", Rep(Nested(Reconfiguration))),
    ("reconfigured", BOOL),
)

Request._spec_ = (("client_id", U64), ("req_no", U64), ("data", BYTES))
RequestAck._spec_ = (("client_id", U64), ("req_no", U64), ("digest", BYTES))

EpochConfig._spec_ = (
    ("number", U64),
    ("leaders", Rep(U64)),
    ("planned_expiration", U64),
)
Checkpoint._spec_ = (("seq_no", U64), ("value", BYTES))
NewEpochConfig._spec_ = (
    ("config", Nested(EpochConfig)),
    ("starting_checkpoint", Nested(Checkpoint)),
    ("final_preprepares", Rep(BYTES)),
)
EpochChangeSetEntry._spec_ = (
    ("epoch", U64),
    ("seq_no", U64),
    ("digest", BYTES),
)
EpochChange._spec_ = (
    ("new_epoch", U64),
    ("checkpoints", Rep(Nested(Checkpoint))),
    ("p_set", Rep(Nested(EpochChangeSetEntry))),
    ("q_set", Rep(Nested(EpochChangeSetEntry))),
)
EpochChangeAck._spec_ = (
    ("originator", U64),
    ("epoch_change", Nested(EpochChange)),
)
RemoteEpochChange._spec_ = (("node_id", U64), ("digest", BYTES))
NewEpoch._spec_ = (
    ("new_config", Nested(NewEpochConfig)),
    ("epoch_changes", Rep(Nested(RemoteEpochChange))),
)

Preprepare._spec_ = (
    ("seq_no", U64),
    ("epoch", U64),
    ("batch", Rep(Nested(RequestAck))),
)
Prepare._spec_ = (("seq_no", U64), ("epoch", U64), ("digest", BYTES))
Commit._spec_ = (("seq_no", U64), ("epoch", U64), ("digest", BYTES))
Suspect._spec_ = (("epoch", U64),)

FetchBatch._spec_ = (("seq_no", U64), ("digest", BYTES))
ForwardBatch._spec_ = (
    ("seq_no", U64),
    ("request_acks", Rep(Nested(RequestAck))),
    ("digest", BYTES),
)
FetchRequest._spec_ = (("client_id", U64), ("req_no", U64), ("digest", BYTES))
ForwardRequest._spec_ = (
    ("request_ack", Nested(RequestAck)),
    ("request_data", BYTES),
)

NewEpochEcho._spec_ = (("new_epoch_config", Nested(NewEpochConfig)),)
NewEpochReady._spec_ = (("new_epoch_config", Nested(NewEpochConfig)),)
Msg._spec_ = (
    (
        "type",
        OneOf(
            (1, Preprepare),
            (2, Prepare),
            (3, Commit),
            (4, Checkpoint),
            (5, Suspect),
            (6, EpochChange),
            (7, EpochChangeAck),
            (8, NewEpoch),
            (9, NewEpochEcho),
            (10, NewEpochReady),
            (11, FetchBatch),
            (12, ForwardBatch),
            (13, FetchRequest),
            (14, ForwardRequest),
            (15, RequestAck),
            allow_unset=False,
        ),
    ),
)

QEntry._spec_ = (
    ("seq_no", U64),
    ("digest", BYTES),
    ("requests", Rep(Nested(RequestAck))),
)
PEntry._spec_ = (("seq_no", U64), ("digest", BYTES))
CEntry._spec_ = (
    ("seq_no", U64),
    ("checkpoint_value", BYTES),
    ("network_state", Nested(NetworkState)),
)
NEntry._spec_ = (("seq_no", U64), ("epoch_config", Nested(EpochConfig)))
FEntry._spec_ = (("ends_epoch_config", Nested(EpochConfig)),)
ECEntry._spec_ = (("epoch_number", U64),)
TEntry._spec_ = (("seq_no", U64), ("value", BYTES))
Persistent._spec_ = (
    (
        "type",
        OneOf(
            (1, QEntry),
            (2, PEntry),
            (3, CEntry),
            (4, NEntry),
            (5, FEntry),
            (6, ECEntry),
            (7, TEntry),
            (8, Suspect),
            allow_unset=False,
        ),
    ),
)

HashOriginRequest._spec_ = (("source", U64), ("request", Nested(Request)))
HashOriginVerifyRequest._spec_ = (
    ("source", U64),
    ("request_ack", Nested(RequestAck)),
    ("request_data", BYTES),
)
HashOriginBatch._spec_ = (
    ("source", U64),
    ("epoch", U64),
    ("seq_no", U64),
    ("request_acks", Rep(Nested(RequestAck))),
)
HashOriginVerifyBatch._spec_ = (
    ("source", U64),
    ("seq_no", U64),
    ("request_acks", Rep(Nested(RequestAck))),
    ("expected_digest", BYTES),
)
HashOriginEpochChange._spec_ = (
    ("source", U64),
    ("origin", U64),
    ("epoch_change", Nested(EpochChange)),
)
HashResult._spec_ = (
    ("digest", BYTES),
    (
        "type",
        OneOf(
            (1, HashOriginRequest),
            (2, HashOriginBatch),
            (3, HashOriginEpochChange),
            (4, HashOriginVerifyBatch),
            (5, HashOriginVerifyRequest),
        ),
    ),
)
CheckpointResult._spec_ = (
    ("seq_no", U64),
    ("value", BYTES),
    ("network_state", Nested(NetworkState)),
    ("reconfigured", BOOL),
)

InitialParameters._spec_ = (
    ("id", U64),
    ("batch_size", U32),
    ("heartbeat_ticks", U32),
    ("suspect_ticks", U32),
    ("new_epoch_timeout_ticks", U32),
    ("buffer_size", U32),
)
EventInitialize._spec_ = (("initial_parms", Nested(InitialParameters)),)
EventLoadEntry._spec_ = (("index", U64), ("data", Nested(Persistent)))
EventLoadRequest._spec_ = (("request_ack", Nested(RequestAck)),)
EventCompleteInitialization._spec_ = ()
EventActionResults._spec_ = (
    ("digests", Rep(Nested(HashResult))),
    ("checkpoints", Rep(Nested(CheckpointResult))),
)
EventTransfer._spec_ = (("c_entry", Nested(CEntry)),)
EventPropose._spec_ = (("request", Nested(Request)),)
EventProposeBatch._spec_ = (("requests", Rep(Nested(Request))),)
EventStep._spec_ = (("source", U64), ("msg", Nested(Msg)))
EventStepBatch._spec_ = (("source", U64), ("msgs", Rep(Nested(Msg))))
EventTick._spec_ = ()
EventActionsReceived._spec_ = ()
StateEvent._spec_ = (
    (
        "type",
        OneOf(
            (1, EventInitialize),
            (2, EventLoadEntry),
            (3, EventLoadRequest),
            (4, EventCompleteInitialization),
            (5, EventActionResults),
            (6, EventTransfer),
            (7, EventPropose),
            (8, EventStep),
            (9, EventTick),
            (10, EventActionsReceived),
            (11, EventStepBatch),
            (12, EventProposeBatch),
            allow_unset=False,
        ),
    ),
)

_ALL_MESSAGES = [
    NetworkConfig,
    NetworkClient,
    ReconfigNewClient,
    ReconfigRemoveClient,
    Reconfiguration,
    NetworkState,
    Request,
    RequestAck,
    EpochConfig,
    Checkpoint,
    NewEpochConfig,
    EpochChangeSetEntry,
    EpochChange,
    EpochChangeAck,
    RemoteEpochChange,
    NewEpoch,
    NewEpochEcho,
    NewEpochReady,
    Preprepare,
    Prepare,
    Commit,
    Suspect,
    FetchBatch,
    ForwardBatch,
    FetchRequest,
    ForwardRequest,
    Msg,
    QEntry,
    PEntry,
    CEntry,
    NEntry,
    FEntry,
    ECEntry,
    TEntry,
    Persistent,
    HashOriginRequest,
    HashOriginVerifyRequest,
    HashOriginBatch,
    HashOriginVerifyBatch,
    HashOriginEpochChange,
    HashResult,
    CheckpointResult,
    InitialParameters,
    EventInitialize,
    EventLoadEntry,
    EventLoadRequest,
    EventCompleteInitialization,
    EventActionResults,
    EventTransfer,
    EventPropose,
    EventProposeBatch,
    EventStep,
    EventStepBatch,
    EventTick,
    EventActionsReceived,
    StateEvent,
]

for _cls in _ALL_MESSAGES:
    wire.check_spec(_cls)


def encode(msg) -> bytes:
    return wire.encode(msg)


def decode(cls, buf: bytes):
    return wire.decode(cls, buf)
