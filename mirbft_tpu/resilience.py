"""Shared fault-tolerance primitives: circuit breaking and backoff.

Two policies used across the stack wherever an unreliable dependency sits
on a hot path:

- ``CircuitBreaker`` guards the crypto planes' device calls
  (testengine/crypto_plane.py, testengine/signing.py): after a run of
  consecutive device failures the breaker *opens* and callers route to the
  host oracle, periodically letting one probe call through (*half-open*)
  to detect recovery.  Probing is count-based, not clock-based, so the
  deterministic testengine stays reproducible from its seed.

- ``Backoff`` paces the transport's reconnect attempts
  (runtime/transport.py): exponential delay growth with full jitter, so a
  mesh of replicas hammering one recovering peer does not synchronize
  into connection storms.
"""

from __future__ import annotations

import random

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Deterministic consecutive-failure circuit breaker.

    States: *closed* (calls allowed), *open* (calls denied; the caller
    uses its fallback), *half-open* (one probe allowed).  ``failure_threshold``
    consecutive failures open the breaker; while open, every
    ``probe_interval``-th denied call is converted into a half-open probe.
    A probe success closes the breaker; a probe failure re-opens it and
    restarts the probe countdown.
    """

    def __init__(self, failure_threshold: int = 3, probe_interval: int = 8):
        assert failure_threshold >= 1 and probe_interval >= 1
        self.failure_threshold = failure_threshold
        self.probe_interval = probe_interval
        self.state = CLOSED
        self.consecutive_failures = 0
        self._denied_since_probe = 0
        # Telemetry (surfaced via status.crypto_plane_status).
        self.failures = 0
        self.successes = 0
        self.trips = 0
        self.probes = 0

    def allow(self) -> bool:
        """Should the caller attempt the guarded dependency right now?"""
        if self.state == CLOSED:
            return True
        if self.state == HALF_OPEN:
            # A probe is already in flight from this caller's perspective;
            # further calls before its verdict use the fallback.
            return False
        self._denied_since_probe += 1
        if self._denied_since_probe >= self.probe_interval:
            self._denied_since_probe = 0
            self.state = HALF_OPEN
            self.probes += 1
            return True
        return False

    def record_success(self) -> None:
        self.successes += 1
        self.consecutive_failures = 0
        self.state = CLOSED

    def record_failure(self) -> None:
        self.failures += 1
        self.consecutive_failures += 1
        if self.state == HALF_OPEN:
            # The probe failed: straight back to open.
            self.state = OPEN
            self._denied_since_probe = 0
        elif self.consecutive_failures >= self.failure_threshold:
            if self.state != OPEN:
                self.trips += 1
            self.state = OPEN
            self._denied_since_probe = 0


class Backoff:
    """Exponential backoff with full jitter (delay drawn uniformly from
    (0, min(cap, base * factor**attempt)]), the AWS-style policy that
    decorrelates retry storms."""

    def __init__(
        self,
        base: float = 0.05,
        factor: float = 2.0,
        cap: float = 2.0,
        rng: random.Random | None = None,
    ):
        self.base = base
        self.factor = factor
        self.cap = cap
        self.attempt = 0
        self._rng = rng or random.Random()

    def next(self) -> float:
        """Delay (seconds) to sleep before the next attempt."""
        ceiling = min(self.cap, self.base * self.factor**self.attempt)
        # Stop growing the exponent once the ceiling has reached the cap:
        # a permanently-dead peer retries forever, and an unbounded
        # ``attempt`` eventually overflows ``factor**attempt`` (a float
        # OverflowError around attempt ~1024 kills the sender thread).
        if ceiling < self.cap:
            self.attempt += 1
        return ceiling * (0.5 + 0.5 * self._rng.random())

    def reset(self) -> None:
        self.attempt = 0
