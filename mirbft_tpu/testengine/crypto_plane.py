"""The coalescing crypto plane: cross-node digest batching for the engine.

The reference executes hashes inline in each node's processor (reference:
processor.go:133-143, testengine/recorder.go:445-455).  On an accelerator
that wastes the device: each node's action batch alone is a handful of
digests, far below the batch sizes that amortize a kernel launch.

The engine gives us slack the reference never used: a hash result does not
re-enter its state machine until ``ready_latency`` simulated milliseconds
after the actions were executed.  Digests are pure functions of data known
at schedule time, so the *computation* can be deferred until the first
result event is actually delivered — and at that point every hash request
accumulated across ALL nodes (typically everything scheduled at the same
simulated instant) flushes as one batched kernel call.

Determinism is untouched: the values are identical to inline execution, so
event counts, recorded logs, and app hash chains come out bit-identical —
the SURVEY §7 determinism-carries-over property, now with real cross-node
coalescing (SURVEY §7 design stance: "coalesced across the action batch and
across concurrently-processing nodes").
"""

from __future__ import annotations

import time

from .. import pb
from ..obsv import hooks
from ..resilience import CircuitBreaker


class DevicePlaneError(Exception):
    """A device digest/verify call failed or returned a short read."""


def _host_digest_many(msgs: list) -> list:
    import hashlib

    return [hashlib.sha256(m).digest() for m in msgs]


class _Lazy:
    """Placeholder for a digest that has been submitted but not computed."""

    __slots__ = ("plane", "index")

    def __init__(self, plane: "CoalescingHashPlane", index: int):
        self.plane = plane
        self.index = index


class CoalescingHashPlane:
    """Deferred digest executor; install via ``Recorder(hash_plane=...)``.

    ``digest_many`` maps a list of byte strings to their SHA-256 digests —
    pass ``ops.sha256.sha256_many`` for the accelerator or leave None for
    host hashlib (useful to isolate the coalescing itself in tests).
    """

    def __init__(self, digest_many=None, breaker=None, timeout_s=None):
        if digest_many is None:
            digest_many = _host_digest_many
        self.digest_many = digest_many
        # Degradation policy: a device batch that raises, returns a short
        # read, or (with timeout_s set) exceeds the deadline counts as a
        # failure; the batch is recomputed on the host oracle and the
        # breaker decides when to stop trying the device altogether (and
        # when to probe it for recovery).  Values are identical either
        # way, so determinism and recorded logs are unaffected.
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.timeout_s = timeout_s
        self._pending: list[bytes] = []  # concatenated preimages
        self._base = 0  # global index of _pending[0]
        self._results: dict[int, bytes] = {}
        # Telemetry for the bench: one entry per flush.
        self.flush_sizes: list[int] = []
        self.flush_wall_s: list[float] = []
        # Fault accounting (surfaced via status.crypto_plane_status).
        self.device_errors = 0
        self.device_timeouts = 0
        self.fallback_digests = 0

    def _guarded_digest_many(self, msgs: list) -> list:
        """Run the digest backend under the circuit breaker; any failure
        falls back to the host oracle so consensus never stalls on a
        lost or lying device."""
        if not self.breaker.allow():
            self.fallback_digests += len(msgs)
            return _host_digest_many(msgs)
        start = time.perf_counter()
        try:
            digests = self.digest_many(msgs)
            if len(digests) != len(msgs):
                raise DevicePlaneError(
                    f"short read: {len(digests)} of {len(msgs)} digests"
                )
        except Exception:
            self.breaker.record_failure()
            self.device_errors += 1
            self.fallback_digests += len(msgs)
            return _host_digest_many(msgs)
        if (
            self.timeout_s is not None
            and time.perf_counter() - start > self.timeout_s
        ):
            # The values are good but the device is too slow to trust on
            # the hot path: count it toward tripping the breaker.
            self.breaker.record_failure()
            self.device_timeouts += 1
        else:
            self.breaker.record_success()
        return digests

    # -- executor side (called from Recorder._execute) -----------------------

    def submit(self, chunk_lists: list) -> list:
        """Queue preimages; returns one placeholder per preimage."""
        handles = []
        for chunks in chunk_lists:
            index = self._base + len(self._pending)
            self._pending.append(b"".join(chunks))
            handles.append(_Lazy(self, index))
        return handles

    def on_time(self, _now: int) -> None:
        """Engine hook at simulated-time advancement; the base plane stays
        fully lazy (the async subclass launches completed waves here)."""

    # -- delivery side (called from Recorder.step) ---------------------------

    def resolve_event(self, event: pb.StateEvent) -> None:
        """Materialize any lazy digests in a results event, in place."""
        if not isinstance(event.type, pb.EventActionResults):
            return
        for hr in event.type.digests:
            if isinstance(hr.digest, _Lazy):
                hr.digest = self._resolve(hr.digest.index)

    def _resolve(self, index: int) -> bytes:
        digest = self._results.get(index)
        if digest is None:
            self._flush()
            digest = self._results[index]
        return digest

    def _flush(self) -> None:
        if not self._pending:
            return
        start = time.perf_counter()
        digests = self._guarded_digest_many(self._pending)
        wall = time.perf_counter() - start
        self.flush_wall_s.append(wall)
        self.flush_sizes.append(len(self._pending))
        if hooks.enabled:
            hooks.record_flush("hash", "batch", len(self._pending), wall)
        for offset, digest in enumerate(digests):
            self._results[self._base + offset] = digest
        self._base += len(self._pending)
        self._pending = []


class AsyncKernelHashPlane(CoalescingHashPlane):
    """The accelerator-backed plane, tuned for steady-state throughput.

    Three refinements over the base class:

    - **Proactive launching.**  Work is grouped by block bucket at submit
      time, and a full chunk launches *immediately* — JAX's async dispatch
      uploads and computes it while the engine keeps processing events, so
      device work overlaps the Python protocol work (the work-pool slack of
      processor.go:183-470 realized as dispatch pipelining).
    - **Fixed launch shapes.**  Each bucket has one chunk row count (sized
      so a launch carries ~``chunk_bytes`` of real data; tails pad up), so
      only one batch shape per block bucket ever reaches the compiler — no
      recompilation storms mid-run (SURVEY §7 hard part 3).
    - **Lazy forcing.**  A chunk's device→host readback happens the first
      time one of its digests is actually needed.

    ``flush_wall_s`` records the blocking time the consumer actually
    experiences per chunk (launch + forced-wait) — the honest
    Actions→Results round-trip latency at the seam.
    """

    def __init__(
        self,
        chunk_rows: int = 8192,
        chunk_bytes: int = 1 << 21,
        kernel_fn=None,
        min_device_rows: int = 4096,
        breaker=None,
        timeout_s=None,
    ):
        super().__init__(digest_many=None, breaker=breaker, timeout_s=timeout_s)
        self.max_chunk_rows = chunk_rows
        self.chunk_bytes = chunk_bytes
        # Digest kernel: fn(blocks, n_blocks) -> (batch, 8) uint32 words.
        # Default is the XLA scan kernel; pass
        # ops.sha256_pallas.sha256_digest_words_pallas for the Pallas one.
        if kernel_fn is None:
            from ..ops.sha256 import sha256_digest_words as kernel_fn
        self.kernel_fn = kernel_fn
        # block bucket -> [(global index, padded words ndarray)]
        self._buckets: dict[int, list] = {}
        # chunk id -> (device words array, [global indices], launch wall s)
        self._inflight: dict[int, tuple] = {}
        self._chunk_of: dict[int, int] = {}  # global index -> chunk id
        self._next_chunk = 0
        # Wave tracking: the engine calls on_time(now) every event; when
        # simulated time moves past the instant work was submitted at, the
        # wave is complete and launches proactively (device + D2H copy run
        # while the engine chews through the hundreds of events between
        # submission and the results delivery ~ready_latency later).
        self._dirty = False
        # Adaptive offload threshold: a device launch only pays off when it
        # can overlap engine progress; a wave smaller than this (and any
        # demand-forced flush, where we are about to block regardless) is
        # cheaper on the host than one tunnel round trip.  Values are
        # identical either way, so determinism and recorded logs are
        # unaffected.
        self.min_device_rows = min_device_rows
        # Overlap telemetry for the bench: device launches (always
        # dispatched in advance of demand), resolve-miss host flushes,
        # and the device/host/rescued digest split.
        self.overlapped_launches = 0
        # Resolve-miss flushes (host-hashed synchronously; see _flush).
        self.demand_flushes = 0
        self.device_digests = 0
        self.host_digests = 0
        self.rescued_digests = 0

    def rows_for(self, bucket: int) -> int:
        """Chunk row count for a block bucket: ~chunk_bytes per launch,
        clamped to [256, max_chunk_rows], power of two."""
        rows = self.chunk_bytes // (bucket * 64)
        rows = 1 << max(8, rows.bit_length() - 1)  # floor pow2, min 256
        return min(self.max_chunk_rows, rows)

    def calibrate(self, probe_rows: int = 512) -> float:
        """Measure the device round trip against host hashlib and set the
        offload break-even threshold.

        The plane is opportunistic (a demand never waits on the device),
        so offloading only pays when a launch can finish before its wave
        is demanded AND carries more digests than the host could compute
        in one round-trip time.  Through a tunneled dev device the RTT is
        tens of ms and the threshold lands in the tens of thousands
        (digests stay host); on a directly attached chip it drops to a
        few hundred.  Returns the measured RTT in seconds."""
        import hashlib

        import jax
        import numpy as np

        from ..ops.batching import pack_preimages

        msgs = [bytes([i % 256]) * 64 for i in range(probe_rows)]
        start = time.perf_counter()
        for m in msgs:
            hashlib.sha256(m).digest()
        host_per_digest = (time.perf_counter() - start) / probe_rows

        packed = pack_preimages(msgs, block_floor=1, batch_floor=1024)
        blocks = jax.device_put(packed.blocks)
        n = jax.device_put(packed.n_blocks)
        np.asarray(self.kernel_fn(blocks, n))  # compile + warm
        start = time.perf_counter()
        packed = pack_preimages(msgs, block_floor=1, batch_floor=1024)
        np.asarray(
            self.kernel_fn(
                jax.device_put(packed.blocks), jax.device_put(packed.n_blocks)
            )
        )
        rtt = time.perf_counter() - start
        # 1.5x safety: a launch below this row count loses to hashlib even
        # if the result arrives in time.
        self.min_device_rows = max(1024, int(1.5 * rtt / host_per_digest))
        return rtt

    # When the calibrated break-even exceeds any feasible wave, the whole
    # deferral machinery is pure overhead: hash inline instead.
    inline_threshold = 65536

    def submit(self, chunk_lists: list) -> list:
        if self.min_device_rows >= self.inline_threshold:
            # Device not worth it on this link (calibrate() measured an
            # RTT the workload's wave sizes cannot amortize): behave like
            # the reference's inline hasher, at hashlib speed.
            import hashlib

            out = [
                hashlib.sha256(b"".join(chunks)).digest()
                for chunks in chunk_lists
            ]
            self.host_digests += len(out)
            if hooks.enabled:
                hooks.record_flush("hash", "inline", len(out))
            return out

        from ..ops.batching import next_pow2, sha256_pad

        handles = []
        for chunks in chunk_lists:
            msg = b"".join(chunks)
            index = self._base
            self._base += 1
            bucket = next_pow2(len(sha256_pad(msg)) // 64)
            group = self._buckets.setdefault(bucket, [])
            group.append((index, msg))
            if len(group) >= self.rows_for(bucket) and len(group) >= (
                self.min_device_rows
            ):
                self._launch(bucket, group)
                self._buckets[bucket] = []
            handles.append(_Lazy(self, index))
        self._dirty = True
        return handles

    def on_time(self, _now: int) -> None:
        """Engine hook, called when simulated time advances: everything
        submitted at earlier instants is a complete wave — launch it now so
        the device (and the async D2H copy) runs while the engine processes
        the events standing between here and the results delivery.  Waves
        below the device threshold hash on the host immediately (see
        min_device_rows)."""
        if self._dirty:
            self._dirty = False
            self._flush(at_wave_boundary=True)

    def _host_hash(self, group: list) -> None:
        import hashlib

        start = time.perf_counter()
        results = self._results
        for index, msg in group:
            results[index] = hashlib.sha256(msg).digest()
        wall = time.perf_counter() - start
        self.flush_wall_s.append(wall)
        self.flush_sizes.append(len(group))
        self.host_digests += len(group)
        if hooks.enabled:
            hooks.record_flush("hash", "host", len(group), wall)

    def _launch(self, bucket: int, group: list) -> None:
        if not self.breaker.allow():
            # Device circuit open: the group degrades to the host oracle
            # (throughput loss, never a stall) until a probe closes it.
            self.fallback_digests += len(group)
            self._host_hash(group)
            return
        try:
            self._launch_device(bucket, group)
        except Exception:
            # Kernel dispatch / device-put blew up (device lost, OOM,
            # compile failure): rescue the whole group on the host.
            self.breaker.record_failure()
            self.device_errors += 1
            self.fallback_digests += len(group)
            self._host_hash(group)

    def _launch_device(self, bucket: int, group: list) -> None:
        import jax

        from ..ops.batching import pack_preimages

        rows = self.rows_for(bucket)
        start = time.perf_counter()
        packed = pack_preimages(
            [msg for _i, msg in group], block_floor=bucket, batch_floor=rows
        )
        words = self.kernel_fn(
            jax.device_put(packed.blocks), jax.device_put(packed.n_blocks)
        )
        try:
            # Start the device->host transfer immediately; by the time a
            # digest is demanded the bytes are (usually) already here.
            words.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # non-jax arrays (tests) or backends without async D2H
        launch_s = time.perf_counter() - start
        indices = [i for i, _msg in group]
        cid = self._next_chunk
        self._next_chunk += 1
        # The preimages ride along so a demand that arrives before the
        # round trip completes can be served by host hashing instead of
        # blocking on the tunnel (identical values either way).
        self._inflight[cid] = (words, group, launch_s, time.perf_counter())
        for i in indices:
            self._chunk_of[i] = cid
        self.flush_sizes.append(len(indices))
        self.overlapped_launches += 1
        self.device_digests += len(indices)
        if hooks.enabled:
            hooks.record_flush("hash", "device", len(indices), launch_s)

    def _flush(self, at_wave_boundary: bool = False) -> None:
        """Flush every partially-filled bucket.  Proactive wave-boundary
        flushes go to the device when big enough to be worth a launch;
        small waves — and every demand-forced flush, which would block for
        a full round trip anyway — hash on the host (strictly faster than
        one tunnel RTT even for thousands of rows)."""
        if not at_wave_boundary:
            self.demand_flushes += 1
        for bucket, group in self._buckets.items():
            if not group:
                continue
            if at_wave_boundary and len(group) >= self.min_device_rows:
                self._launch(bucket, group)
            else:
                self._host_hash(group)
            self._buckets[bucket] = []

    def _resolve(self, index: int) -> bytes:
        digest = self._results.get(index)
        if digest is not None:
            return digest
        if index not in self._chunk_of:
            self._flush()
            # Demand flushes host-hash straight into _results (no chunk is
            # registered for them) — recheck before assuming a chunk.
            digest = self._results.get(index)
            if digest is not None:
                return digest
        cid = self._chunk_of[index]
        words, group, launch_s, launched_at = self._inflight.pop(cid)
        start = time.perf_counter()
        results = self._results
        try:
            ready = words.is_ready()
        except AttributeError:
            ready = True  # non-jax arrays (tests): materialized already
        if not ready:
            # The round trip has not finished: never stall the event loop
            # on the device.  Recompute on the host (µs–ms per digest) and
            # let the device result drop — the offload is opportunistic;
            # it only counts when it beats the demand.  (Values are
            # identical either way, so determinism is unaffected.)
            import hashlib

            for i, msg in group:
                results[i] = hashlib.sha256(msg).digest()
                del self._chunk_of[i]
            self.rescued_digests += len(group)
            self.device_digests -= len(group)
            wall = launch_s + time.perf_counter() - start
            self.flush_wall_s.append(wall)
            if hooks.enabled:
                hooks.record_flush("hash", "rescued", len(group), wall)
            return results[index]
        import numpy as np

        try:
            raw = np.asarray(words).astype(">u4").tobytes()
            if len(raw) < 32 * len(group):
                raise DevicePlaneError(
                    f"short readback: {len(raw)} bytes for {len(group)} rows"
                )
        except Exception:
            # The device died (or lied) between launch and readback: the
            # preimages ride along with the chunk, so rescue on the host
            # and charge the breaker.
            import hashlib

            self.breaker.record_failure()
            self.device_errors += 1
            for i, msg in group:
                results[i] = hashlib.sha256(msg).digest()
                del self._chunk_of[i]
            self.rescued_digests += len(group)
            self.device_digests -= len(group)
            self.fallback_digests += len(group)
            wall = launch_s + time.perf_counter() - start
            self.flush_wall_s.append(wall)
            if hooks.enabled:
                hooks.record_flush("hash", "rescued", len(group), wall)
            return results[index]
        self.breaker.record_success()
        wall = launch_s + time.perf_counter() - start
        self.flush_wall_s.append(wall)
        if hooks.enabled:
            hooks.record_flush("hash", "readback", len(group), wall)
        for row, (i, _msg) in enumerate(group):
            results[i] = raw[32 * row : 32 * row + 32]
            del self._chunk_of[i]
        return results[index]
