"""Fault-injection mangler DSL for the testengine.

Rebuild of the reference's mangler language (reference:
testengine/manglers.go:45-718): composable predicates over scheduled
events, temporal combinators, and the actions Drop / Delay / Jitter /
Duplicate / CrashAndRestartAfter.  A mangler is a callable
``(recorder, when, node, event) -> verdict`` where the verdict is ``None``
(drop), one ``(when, node, event)`` tuple, or a list of tuples
(duplication); the engine folds the candidate set through every mangler
(engine._schedule).

All randomness draws from ``recorder.rng`` so mangled runs stay
reproducible from the seed.

Usage (mirroring the reference's scenarios, mirbft_test.go:68-222)::

    rule(is_step()).jitter(30)                              # 30ms jitter
    rule(is_step(), percent(75)).duplicate(300)             # 75% duplication
    rule(msg_type("RequestAck"), from_source(1, 2),
         percent(70)).drop()                                # targeted ack loss
    rule(to_node(1), after_events(30), once()
         ).crash_and_restart_after(5000)                    # crash + reboot
"""

from __future__ import annotations

from .. import pb


# ---------------------------------------------------------------------------
# Predicates: (recorder, when, node, event) -> bool
# ---------------------------------------------------------------------------


def is_step():
    """Matches inbound network messages (EventStep) — what 'the network'
    can observe and disturb."""

    def pred(_recorder, _when, _node, event):
        return isinstance(event.type, pb.EventStep)

    return pred


def event_type(*names: str):
    def pred(_recorder, _when, _node, event):
        return type(event.type).__name__ in names

    return pred


def msg_type(*names: str):
    """Matches EventStep events carrying one of these message kinds."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        return (
            isinstance(inner, pb.EventStep)
            and inner.msg is not None
            and type(inner.msg.type).__name__ in names
        )

    return pred


def from_source(*sources: int):
    """Matches EventStep events sent by one of these nodes."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        return isinstance(inner, pb.EventStep) and inner.source in sources

    return pred


def to_node(*nodes: int):
    """Matches events delivered to one of these nodes."""

    def pred(_recorder, _when, node, _event):
        return node in nodes

    return pred


def from_client(*client_ids: int):
    """Matches proposals and request acks of these clients."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        if isinstance(inner, pb.EventPropose) and inner.request is not None:
            return inner.request.client_id in client_ids
        if (
            isinstance(inner, pb.EventStep)
            and inner.msg is not None
            and isinstance(inner.msg.type, pb.RequestAck)
        ):
            return inner.msg.type.client_id in client_ids
        return False

    return pred


def with_seq_no(low: int, high: int):
    """Matches 3-phase messages whose seq_no lies in [low, high]."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        if not isinstance(inner, pb.EventStep) or inner.msg is None:
            return False
        msg = inner.msg.type
        seq = getattr(msg, "seq_no", None)
        return seq is not None and low <= seq <= high

    return pred


def percent(p: float):
    """Matches p% of the events reaching it (seeded rng)."""

    def pred(recorder, _when, _node, _event):
        return recorder.rng.random() * 100 < p

    return pred


# Temporal combinators (stateful; one instance per rule).


def after_events(n: int):
    """Matches only from the n-th candidate event this predicate sees."""
    seen = [0]

    def pred(_recorder, _when, _node, _event):
        seen[0] += 1
        return seen[0] > n

    return pred


def until_events(n: int):
    """Matches only the first n candidate events this predicate sees."""
    seen = [0]

    def pred(_recorder, _when, _node, _event):
        seen[0] += 1
        return seen[0] <= n

    return pred


def after_time(ms: int):
    def pred(_recorder, when, _node, _event):
        return when >= ms

    return pred


def until_time(ms: int):
    def pred(_recorder, when, _node, _event):
        return when < ms

    return pred


def once():
    """Matches exactly one event (combine after the other predicates)."""
    fired = [False]

    def pred(_recorder, _when, _node, _event):
        if fired[0]:
            return False
        fired[0] = True
        return True

    return pred


# ---------------------------------------------------------------------------
# Rules and actions
# ---------------------------------------------------------------------------


class _Rule:
    """Predicates are AND-ed left to right; later (stateful temporal)
    predicates only see events the earlier ones matched — so
    ``rule(msg_type("Prepare"), until_events(5))`` means 'the first five
    Prepares', like the reference's fluent chains."""

    def __init__(self, predicates):
        self.predicates = list(predicates)

    def _matches(self, recorder, when, node, event) -> bool:
        return all(
            predicate(recorder, when, node, event)
            for predicate in self.predicates
        )

    def drop(self):
        """Drops matched events.  The returned mangler counts casualties on
        its ``dropped`` attribute (mirrors partition())."""

        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                mangler.dropped += 1
                return None
            return (when, node, event)

        mangler.dropped = 0
        return mangler

    def delay(self, ms: int):
        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                return (when + ms, node, event)
            return (when, node, event)

        return mangler

    def jitter(self, max_ms: int):
        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                return (when + recorder.rng.randint(0, max_ms), node, event)
            return (when, node, event)

        return mangler

    def duplicate(self, max_delay_ms: int):
        """Duplicates matched events with a delayed echo.  The returned
        mangler counts echoes on its ``duplicated`` attribute."""

        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                echo = when + recorder.rng.randint(1, max(max_delay_ms, 1))
                mangler.duplicated += 1
                return [(when, node, event), (echo, node, event)]
            return (when, node, event)

        mangler.duplicated = 0
        return mangler

    def crash_and_restart_after(self, delay_ms: int, node: int | None = None):
        """On match, crash the event's target node (or the given node) and
        boot it from its durable state delay_ms later (reference:
        manglers.go:696-718, which injects a fresh Initialize).  Combine
        with once() unless repeated crashes are intended."""

        def mangler(recorder, when, target, event):
            if self._matches(recorder, when, target, event):
                victim = node if node is not None else target
                recorder.crash(victim)
                recorder.schedule_restart(victim, delay_ms)
                return None  # the triggering event dies with the node
            return (when, target, event)

        return mangler


def rule(*predicates) -> _Rule:
    return _Rule(predicates)


# ---------------------------------------------------------------------------
# Network partitions
# ---------------------------------------------------------------------------


def crosses_partition(groups):
    """Matches EventStep messages whose source and destination lie in
    *different* groups.  ``groups`` is an iterable of node-id collections;
    a node appearing in no group is unaffected (its traffic always
    passes), so ``[[0], [1, 2, 3]]`` isolates node 0 from the rest."""
    group_of: dict[int, int] = {}
    for gi, members in enumerate(groups):
        for member in members:
            group_of[member] = gi

    def pred(_recorder, _when, node, event):
        inner = event.type
        if not isinstance(inner, pb.EventStep):
            return False
        src = group_of.get(inner.source)
        dst = group_of.get(node)
        return src is not None and dst is not None and src != dst

    return pred


def partition(groups, from_ms: int = 0, until_ms: int | None = None):
    """Network partition with heal: every inter-group EventStep during
    [from_ms, until_ms) is dropped; traffic before the split and after the
    heal flows normally.  ``until_ms=None`` never heals.  Messages lost to
    the partition are gone for good — post-heal progress relies on the
    protocol's retransmission ticks, which is exactly the liveness property
    the chaos invariants assert.  The returned mangler counts casualties on
    its ``dropped`` attribute."""
    cross = crosses_partition(groups)

    def mangler(recorder, when, node, event):
        if (
            when >= from_ms
            and (until_ms is None or when < until_ms)
            and cross(recorder, when, node, event)
        ):
            mangler.dropped += 1
            return None
        return (when, node, event)

    mangler.dropped = 0
    return mangler
