"""Fault-injection mangler DSL for the testengine.

Rebuild of the reference's mangler language (reference:
testengine/manglers.go:45-718): composable predicates over scheduled
events, temporal combinators, and the actions Drop / Delay / Jitter /
Duplicate / CrashAndRestartAfter.  A mangler is a callable
``(recorder, when, node, event) -> verdict`` where the verdict is ``None``
(drop), one ``(when, node, event)`` tuple, or a list of tuples
(duplication); the engine folds the candidate set through every mangler
(engine._schedule).

All randomness draws from ``recorder.rng`` so mangled runs stay
reproducible from the seed.

Usage (mirroring the reference's scenarios, mirbft_test.go:68-222)::

    rule(is_step()).jitter(30)                              # 30ms jitter
    rule(is_step(), percent(75)).duplicate(300)             # 75% duplication
    rule(msg_type("RequestAck"), from_source(1, 2),
         percent(70)).drop()                                # targeted ack loss
    rule(to_node(1), after_events(30), once()
         ).crash_and_restart_after(5000)                    # crash + reboot
"""

from __future__ import annotations

from .. import pb


# ---------------------------------------------------------------------------
# Predicates: (recorder, when, node, event) -> bool
# ---------------------------------------------------------------------------


def is_step():
    """Matches inbound network messages (EventStep) — what 'the network'
    can observe and disturb."""

    def pred(_recorder, _when, _node, event):
        return isinstance(event.type, pb.EventStep)

    return pred


def event_type(*names: str):
    def pred(_recorder, _when, _node, event):
        return type(event.type).__name__ in names

    return pred


def msg_type(*names: str):
    """Matches EventStep events carrying one of these message kinds."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        return (
            isinstance(inner, pb.EventStep)
            and inner.msg is not None
            and type(inner.msg.type).__name__ in names
        )

    return pred


def from_source(*sources: int):
    """Matches EventStep events sent by one of these nodes."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        return isinstance(inner, pb.EventStep) and inner.source in sources

    return pred


def to_node(*nodes: int):
    """Matches events delivered to one of these nodes."""

    def pred(_recorder, _when, node, _event):
        return node in nodes

    return pred


def from_client(*client_ids: int):
    """Matches proposals, request acks, and forwarded requests of these
    clients — every event through which a node can learn of a client's
    request, which is exactly the surface a censoring leader suppresses."""

    def pred(_recorder, _when, _node, event):
        pair = request_identity(event)
        return pair is not None and pair[0] in client_ids

    return pred


def is_propose():
    """Matches local client-ingress proposals (EventPropose)."""

    def pred(_recorder, _when, _node, event):
        return isinstance(event.type, pb.EventPropose)

    return pred


def with_seq_no(low: int, high: int):
    """Matches 3-phase messages whose seq_no lies in [low, high]."""

    def pred(_recorder, _when, _node, event):
        inner = event.type
        if not isinstance(inner, pb.EventStep) or inner.msg is None:
            return False
        msg = inner.msg.type
        seq = getattr(msg, "seq_no", None)
        return seq is not None and low <= seq <= high

    return pred


def percent(p: float):
    """Matches p% of the events reaching it (seeded rng)."""

    def pred(recorder, _when, _node, _event):
        return recorder.rng.random() * 100 < p

    return pred


# Temporal combinators (stateful; one instance per rule).


def after_events(n: int):
    """Matches only from the n-th candidate event this predicate sees."""
    seen = [0]

    def pred(_recorder, _when, _node, _event):
        seen[0] += 1
        return seen[0] > n

    return pred


def until_events(n: int):
    """Matches only the first n candidate events this predicate sees."""
    seen = [0]

    def pred(_recorder, _when, _node, _event):
        seen[0] += 1
        return seen[0] <= n

    return pred


def after_time(ms: int):
    def pred(_recorder, when, _node, _event):
        return when >= ms

    return pred


def until_time(ms: int):
    def pred(_recorder, when, _node, _event):
        return when < ms

    return pred


def once():
    """Matches exactly one event (combine after the other predicates)."""
    fired = [False]

    def pred(_recorder, _when, _node, _event):
        if fired[0]:
            return False
        fired[0] = True
        return True

    return pred


# ---------------------------------------------------------------------------
# Adversarial helpers
# ---------------------------------------------------------------------------


def request_identity(event) -> tuple[int, int] | None:
    """The (client_id, req_no) a request-carrying event speaks for, or None.

    Covers the three delivery paths a request can take to a node: local
    proposal (EventPropose), ack gossip (RequestAck), and data forwarding
    (ForwardRequest)."""
    inner = event.type
    if isinstance(inner, pb.EventPropose) and inner.request is not None:
        req = inner.request
        return (req.client_id, req.req_no)
    if isinstance(inner, pb.EventStep) and inner.msg is not None:
        msg = inner.msg.type
        if isinstance(msg, pb.RequestAck):
            return (msg.client_id, msg.req_no)
        if isinstance(msg, pb.ForwardRequest) and msg.request_ack is not None:
            ack = msg.request_ack
            return (ack.client_id, ack.req_no)
    return None


def _flip_bytes(data: bytes, rng, flips: int) -> bytes:
    """Returns data with up to ``flips`` bytes XOR-ed against nonzero masks
    (seeded rng) — guaranteed != data whenever data is non-empty."""
    if not data:
        return data
    mutated = bytearray(data)
    for _ in range(max(flips, 1)):
        pos = rng.randrange(len(mutated))
        mutated[pos] ^= rng.randint(1, 255)
    return bytes(mutated)


def _variant_digest(digest: bytes) -> bytes:
    """Deterministic conflicting digest for an equivocated batch: same for
    every victim of the same (epoch, seq), so the equivocating leader tells
    one consistent lie per subset — the hardest case for fork detection."""
    if not digest:
        return b"\xff"
    return digest[:-1] + bytes([digest[-1] ^ 0xFF])


def _restep(inner: "pb.EventStep", msg) -> "pb.StateEvent":
    """A fresh EventStep event carrying ``msg`` from the same source; never
    mutates the original (other targets share the event object)."""
    return pb.StateEvent(
        type=pb.EventStep(source=inner.source, msg=pb.Msg(type=msg))
    )


# ---------------------------------------------------------------------------
# Rules and actions
# ---------------------------------------------------------------------------


class _Rule:
    """Predicates are AND-ed left to right; later (stateful temporal)
    predicates only see events the earlier ones matched — so
    ``rule(msg_type("Prepare"), until_events(5))`` means 'the first five
    Prepares', like the reference's fluent chains."""

    def __init__(self, predicates):
        self.predicates = list(predicates)

    def _matches(self, recorder, when, node, event) -> bool:
        return all(
            predicate(recorder, when, node, event)
            for predicate in self.predicates
        )

    def drop(self):
        """Drops matched events.  The returned mangler counts casualties on
        its ``dropped`` attribute (mirrors partition())."""

        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                mangler.dropped += 1
                return None
            return (when, node, event)

        mangler.dropped = 0
        return mangler

    def delay(self, ms: int):
        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                return (when + ms, node, event)
            return (when, node, event)

        return mangler

    def jitter(self, max_ms: int):
        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                return (when + recorder.rng.randint(0, max_ms), node, event)
            return (when, node, event)

        return mangler

    def duplicate(self, max_delay_ms: int):
        """Duplicates matched events with a delayed echo.  The returned
        mangler counts echoes on its ``duplicated`` attribute."""

        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                echo = when + recorder.rng.randint(1, max(max_delay_ms, 1))
                mangler.duplicated += 1
                return [(when, node, event), (echo, node, event)]
            return (when, node, event)

        mangler.duplicated = 0
        return mangler

    def corrupt(self, byte_flips: int = 1):
        """Flips payload/digest bytes of matched events in flight (seeded
        rng), modelling a compromised link or leader that tampers with
        content rather than delivery.  Rewrites — never mutates — the event:

        * EventPropose: the request data (signed mode must reject it);
        * RequestAck / Prepare / Commit: the digest;
        * ForwardRequest: the forwarded request data (the receiver's digest
          re-verification must drop it);
        * Preprepare: one batch entry's digest.

        Counts rewrites on ``corrupted``, and the EventPropose subset —
        the deliveries a signature plane is obligated to reject — on
        ``corrupted_proposes``."""

        def mangler(recorder, when, node, event):
            if not self._matches(recorder, when, node, event):
                return (when, node, event)
            rng = recorder.rng
            inner = event.type
            if isinstance(inner, pb.EventPropose) and inner.request is not None:
                req = inner.request
                twisted = _flip_bytes(req.data, rng, byte_flips)
                if twisted == req.data:
                    return (when, node, event)
                mangler.corrupted += 1
                mangler.corrupted_proposes += 1
                forged = pb.Request(
                    client_id=req.client_id, req_no=req.req_no, data=twisted
                )
                return (when, node, pb.StateEvent(type=pb.EventPropose(request=forged)))
            if isinstance(inner, pb.EventStep) and inner.msg is not None:
                msg = inner.msg.type
                if isinstance(msg, pb.RequestAck):
                    mangler.corrupted += 1
                    forged = pb.RequestAck(
                        client_id=msg.client_id,
                        req_no=msg.req_no,
                        digest=_flip_bytes(msg.digest, rng, byte_flips),
                    )
                    return (when, node, _restep(inner, forged))
                if isinstance(msg, (pb.Prepare, pb.Commit)):
                    mangler.corrupted += 1
                    forged = type(msg)(
                        seq_no=msg.seq_no,
                        epoch=msg.epoch,
                        digest=_flip_bytes(msg.digest, rng, byte_flips),
                    )
                    return (when, node, _restep(inner, forged))
                if isinstance(msg, pb.ForwardRequest) and msg.request_ack is not None:
                    mangler.corrupted += 1
                    forged = pb.ForwardRequest(
                        request_ack=msg.request_ack,
                        request_data=_flip_bytes(msg.request_data, rng, byte_flips),
                    )
                    return (when, node, _restep(inner, forged))
                if isinstance(msg, pb.Preprepare) and msg.batch:
                    mangler.corrupted += 1
                    victim = rng.randrange(len(msg.batch))
                    batch = list(msg.batch)
                    ack = batch[victim]
                    batch[victim] = pb.RequestAck(
                        client_id=ack.client_id,
                        req_no=ack.req_no,
                        digest=_flip_bytes(ack.digest, rng, byte_flips),
                    )
                    forged = pb.Preprepare(
                        seq_no=msg.seq_no, epoch=msg.epoch, batch=batch
                    )
                    return (when, node, _restep(inner, forged))
            return (when, node, event)

        mangler.corrupted = 0
        mangler.corrupted_proposes = 0
        return mangler

    def equivocate(self, victims):
        """The matched Preprepare's sender lies to ``victims``: they receive
        a conflicting batch (every digest swapped for a deterministic
        variant) for the same (epoch, seq), while other nodes see the real
        one — the paper's equivocating-leader attack.  The variant digests
        reference no existing request, so a victim can never assemble the
        batch: either the honest subset still reaches quorum (victims catch
        up via state transfer) or the sequence stalls and the suspect
        machinery rotates the liar out.  Counts rewrites on ``equivocated``
        and records {(epoch, seq): (real digests, variant digests)} on
        ``variants`` for the no-fork audit."""
        victim_set = frozenset(victims)

        def mangler(recorder, when, node, event):
            if node in victim_set and self._matches(recorder, when, node, event):
                inner = event.type
                if (
                    isinstance(inner, pb.EventStep)
                    and inner.msg is not None
                    and isinstance(inner.msg.type, pb.Preprepare)
                    and inner.msg.type.batch
                ):
                    msg = inner.msg.type
                    batch = [
                        pb.RequestAck(
                            client_id=a.client_id,
                            req_no=a.req_no,
                            digest=_variant_digest(a.digest),
                        )
                        for a in msg.batch
                    ]
                    mangler.equivocated += 1
                    mangler.variants[(msg.epoch, msg.seq_no)] = (
                        tuple(a.digest for a in msg.batch),
                        tuple(a.digest for a in batch),
                    )
                    forged = pb.Preprepare(
                        seq_no=msg.seq_no, epoch=msg.epoch, batch=batch
                    )
                    return (when, node, _restep(inner, forged))
            return (when, node, event)

        mangler.equivocated = 0
        mangler.variants = {}
        return mangler

    def censor(self):
        """Silently drops matched request-carrying events — a censoring
        leader suppressing targeted clients at ingress.  Unlike ``drop()``
        it only swallows events that speak for a request (proposals, acks,
        forwards) and records which (client_id, req_no) pairs were censored
        on ``censored_pairs``, so the liveness audit can assert each one
        still commits once bucket rotation hands the bucket to an honest
        leader.  Combine with ``to_node(leader)`` + ``from_client(...)``."""

        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                pair = request_identity(event)
                if pair is not None:
                    mangler.censored += 1
                    mangler.censored_pairs.add(pair)
                    return None
            return (when, node, event)

        mangler.censored = 0
        mangler.censored_pairs = set()
        return mangler

    def flood(self, copies: int, max_delay_ms: int):
        """Duplication / stale-ack storm: every matched event is delivered,
        plus ``copies`` echoes spread over (0, max_delay_ms] (seeded rng).
        With a large delay the echoes arrive long after the original
        committed — the paper's stale-ack attack on the dedup path.  Counts
        echoes on ``flooded``."""

        def mangler(recorder, when, node, event):
            if self._matches(recorder, when, node, event):
                out = [(when, node, event)]
                for _ in range(copies):
                    echo = when + recorder.rng.randint(1, max(max_delay_ms, 1))
                    out.append((echo, node, event))
                mangler.flooded += copies
                return out
            return (when, node, event)

        mangler.flooded = 0
        return mangler

    def crash_and_restart_after(self, delay_ms: int, node: int | None = None):
        """On match, crash the event's target node (or the given node) and
        boot it from its durable state delay_ms later (reference:
        manglers.go:696-718, which injects a fresh Initialize).  Combine
        with once() unless repeated crashes are intended."""

        def mangler(recorder, when, target, event):
            if self._matches(recorder, when, target, event):
                victim = node if node is not None else target
                recorder.crash(victim)
                recorder.schedule_restart(victim, delay_ms)
                return None  # the triggering event dies with the node
            return (when, target, event)

        return mangler


def rule(*predicates) -> _Rule:
    return _Rule(predicates)


# ---------------------------------------------------------------------------
# Network partitions
# ---------------------------------------------------------------------------


def crosses_partition(groups):
    """Matches EventStep messages whose source and destination lie in
    *different* groups.  ``groups`` is an iterable of node-id collections;
    a node appearing in no group is unaffected (its traffic always
    passes), so ``[[0], [1, 2, 3]]`` isolates node 0 from the rest."""
    group_of: dict[int, int] = {}
    for gi, members in enumerate(groups):
        for member in members:
            group_of[member] = gi

    def pred(_recorder, _when, node, event):
        inner = event.type
        if not isinstance(inner, pb.EventStep):
            return False
        src = group_of.get(inner.source)
        dst = group_of.get(node)
        return src is not None and dst is not None and src != dst

    return pred


def partition(groups, from_ms: int = 0, until_ms: int | None = None):
    """Network partition with heal: every inter-group EventStep during
    [from_ms, until_ms) is dropped; traffic before the split and after the
    heal flows normally.  ``until_ms=None`` never heals.  Messages lost to
    the partition are gone for good — post-heal progress relies on the
    protocol's retransmission ticks, which is exactly the liveness property
    the chaos invariants assert.  The returned mangler counts casualties on
    its ``dropped`` attribute."""
    cross = crosses_partition(groups)

    def mangler(recorder, when, node, event):
        if (
            when >= from_ms
            and (until_ms is None or when < until_ms)
            and cross(recorder, when, node, event)
        ):
            mangler.dropped += 1
            return None
        return (when, node, event)

    mangler.dropped = 0
    return mangler
