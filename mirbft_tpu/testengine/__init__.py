"""Deterministic multi-node discrete-event simulator.

The rebuild of the reference's highest-leverage test asset (reference:
testengine/).  Because the protocol core is a pure function StateEvent →
Actions with no hidden inputs, N "nodes" are just N state-machine values
advanced by one time-ordered event queue with modeled latencies — epoch
changes, state transfer, crashes, and adversarial networks are exercised
in-process, reproducibly, from a seed.  Fixed seed ⇒ fixed event count ⇒
fixed final app hash, asserted by the determinism gates in
tests/test_testengine.py.
"""

from .engine import BasicRecorder, Recorder, RuntimeParameters  # noqa: F401
