"""The discrete-event simulation engine.

Rebuild of the reference's recorder/player (reference:
testengine/recorder.go:41-685, testengine/player.go).  One time-ordered
event queue drives N bare StateMachines; the environment around them — WAL,
request store, app log, hashing, the network — is modeled with configurable
latencies.  All randomness comes from a seed; the wall clock is never read.

Consequence scheduling per executed Actions (mirroring the runtime's
processor contract, docs/Processor.md):
- persists apply to the node's model WAL immediately (durability modeled as
  ``persist_latency`` added before dependent sends);
- sends become Step events at ``+persist_latency+link_latency`` (self
  deliveries too: the executor loops self-sends back through Step);
- hashes are computed inline and return as one ActionResults event at
  ``+ready_latency``;
- commits apply to a per-node SHA-256 hash chain; checkpoint requests
  compute the chain value and return with the same ActionResults event;
- forward-requests read the node's request store and send ForwardRequest
  messages;
- state transfer is served from any node's checkpoint store at
  ``+state_transfer_latency``.
"""

from __future__ import annotations

import contextlib
import gc
import hashlib
import heapq
import random
from dataclasses import dataclass, field


@contextlib.contextmanager
def _gc_paused():
    """Pause the cyclic collector for the duration of a drain loop.

    The engine allocates millions of (almost entirely acyclic) events,
    actions, and tracker records per run; generational GC repeatedly scans
    the large live graph and costs ~40% of drain wall clock at ladder
    scale.  The few real cycles (Recorder back-references) persist until
    the resumed collector's next threshold-triggered pass."""
    was_enabled = gc.isenabled()
    if was_enabled:
        gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()

from .. import pb
from ..core import actions as act
from ..core.preimage import host_digest
from ..core.state_machine import StateMachine
from ..obsv import hooks


@dataclass
class RuntimeParameters:
    """Latency model, in simulated milliseconds (reference defaults:
    testengine/recorder.go:649-656)."""

    tick_interval: int = 500
    link_latency: int = 100
    ready_latency: int = 50
    process_latency: int = 10
    persist_latency: int = 10
    state_transfer_latency: int = 800
    # WAN delay variance, applied per delivered frame (uniform in
    # [0, link_jitter], drawn from the engine's seeded rng).  Frame-level
    # because a frame models one transport segment: per-msg jitter (the
    # manglers' fault-injection semantics) would tear every coalesced
    # delivery into individual events, which is neither how packet delay
    # variation behaves nor affordable at pod scale.
    link_jitter: int = 0


def standard_initial_network_state(
    node_count: int, client_ids: list
) -> pb.NetworkState:
    """Default protocol constants (reference: mirbft.go:125-154):
    buckets = nodes, ci = 5*buckets, max epoch length = 10*ci, width 100."""
    buckets = node_count
    ci = 5 * buckets
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(node_count)),
            f=(node_count - 1) // 3,
            number_of_buckets=buckets,
            checkpoint_interval=ci,
            max_epoch_length=10 * ci,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=100, low_watermark=0)
            for cid in client_ids
        ],
    )


@dataclass
class NodeState:
    """Environment model for one node."""

    wal: list = field(default_factory=list)  # [(index, pb.Persistent)]
    wal_truncated_to: int = 0
    reqstore: dict = field(default_factory=dict)  # digest -> (ack, data)
    app_chain: bytes = b""  # rolling SHA-256 hash chain of applied batches
    last_committed: int = 0
    checkpoints: dict = field(default_factory=dict)  # seq -> (value, state)
    committed_reqs: list = field(default_factory=list)  # [(client, req_no, seq)]
    crashed: bool = False
    # Reconfigurations the app observed committed in the current checkpoint
    # window (reported with the next CheckpointResult, reference:
    # actions.go:234-261).
    pending_reconfigs: list = field(default_factory=list)
    # Actions accumulated since the last executor pass.  The executor runs
    # once per ``process_latency`` window over everything accumulated —
    # the reference serializer's Actions accumulation between Ready()
    # reads (reference: serializer.go:216-223) — which is what lets sends
    # coalesce per target and hashes batch per launch.
    pending: act.Actions = field(default_factory=act.Actions)
    process_scheduled: bool = False


class _ClientState:
    def __init__(self, client_id: int, total_reqs: int = 0, owner=None):
        self.client_id = client_id
        self.next_req_no = 0
        self._owner = owner  # Recorder, for total-reqs cache invalidation
        self._total_reqs = total_reqs
        # node -> set of this client's req_nos seen committed there
        self.committed_by_node: dict = {}
        # req_nos committed anywhere (drives window refill exactly once)
        self.committed_anywhere: set = set()

    @property
    def total_reqs(self) -> int:
        return self._total_reqs

    @total_reqs.setter
    def total_reqs(self, value: int) -> None:
        # Direct assignment must invalidate the Recorder's cached total —
        # tests legitimately shorten a removed client's stream this way.
        self._total_reqs = value
        if self._owner is not None:
            self._owner._total_reqs_cache = None
            self._owner._progress = True

    def request(self, req_no: int) -> pb.Request:
        # Deterministic payload, distinct per (client, req_no).
        data = b"%d:%d" % (self.client_id, req_no)
        if self._owner is not None and self._owner.signer is not None:
            data = self._owner.signer(self.client_id, req_no, data)
        return pb.Request(client_id=self.client_id, req_no=req_no, data=data)


class Recorder:
    """Drives a simulated network to full commitment, recording every event."""

    def __init__(
        self,
        node_count: int,
        client_count: int,
        reqs_per_client: int,
        params: RuntimeParameters | None = None,
        seed: int = 0,
        batch_size: int = 1,
        interceptor=None,
        manglers=(),
        hash_executor=None,
        hash_plane=None,
        signer=None,
        signature_plane=None,
        mac_plane=None,
        network_state=None,
        checkpoint_certs=None,
        record=True,
        deferred_nodes=(),
    ):
        self.params = params or RuntimeParameters()
        self.rng = random.Random(seed)
        self.node_count = node_count
        self.reqs_per_client = reqs_per_client
        self.batch_size = batch_size
        self.interceptor = interceptor
        self.manglers = list(manglers)
        # Pluggable digest executor: fn(list of chunk-lists) -> list of
        # digests.  Default is host hashlib; passing ops.sha256.sha256_chunked
        # runs every digest of the simulation on the accelerator — event
        # counts and app chains must come out identical (determinism carries
        # over the Actions seam, SURVEY §7).
        self.hash_executor = hash_executor
        # Deferred cross-node digest batching (crypto_plane.py): digests are
        # computed lazily at result-delivery time, coalescing everything
        # pending across all nodes into one kernel call.  Mutually exclusive
        # with hash_executor; values (and thus logs) are identical either way.
        self.hash_plane = hash_plane
        # Signed-request mode (signing.py): clients sign, and replicas
        # authenticate each Propose at ingress — the consumer-side auth the
        # reference mandates (mirbft.go:297-301) — via a deferred batched
        # SignaturePlane.  Invalid requests are dropped before the state
        # machine sees them.
        self.signer = signer
        self.signature_plane = signature_plane
        # MAC-authenticated replica channels (signing.MacSealPlane): every
        # legitimately sent node-to-node message is sealed at emission and
        # checked at delivery; mangler-forged rewrites are unsealed and
        # dropped at ingress, mirroring the live transport's per-link MAC
        # rejection (docs/CRYPTO.md).  Opt-in per scenario: the default
        # None keeps digest-layer corruption scenarios observing their
        # evidence where they always did.
        self.mac_plane = mac_plane
        # Checkpoint quorum certificates (certs.py): every Checkpoint
        # broadcast doubles as a BLS vote; 2f+1 matching votes aggregate
        # into one constant-size certificate.
        self.checkpoint_certs = checkpoint_certs

        # Default protocol constants scale buckets/ci with the node count
        # (reference: mirbft.go:125-154); very large networks pass an
        # explicit network_state to tame the O(buckets * n^2) heartbeat
        # traffic (fewer leaders, smaller checkpoint interval).  Client ids
        # always come from the replicated state so the simulated clients
        # and the protocol config agree by construction.
        if network_state is not None:
            self.initial_state = network_state
            client_ids = [c.id for c in network_state.clients]
            assert len(client_ids) == client_count, (
                f"network_state declares {len(client_ids)} clients, "
                f"client_count={client_count}"
            )
            # The simulated universe may be a superset of the configured
            # member set, but only by the explicitly deferred nodes
            # (replicas that join later via a node-set reconfiguration,
            # see provision_node) — a live non-member would hang at drain
            # instead of failing fast.
            members = set(network_state.config.nodes)
            assert members <= set(range(node_count)), (
                f"network_state declares nodes "
                f"{network_state.config.nodes}, engine simulates "
                f"0..{node_count - 1}"
            )
            assert set(range(node_count)) - members <= set(
                deferred_nodes
            ), (
                f"nodes {sorted(set(range(node_count)) - members)} are "
                f"simulated but neither configured members nor deferred"
            )
        else:
            client_ids = [node_count + i for i in range(client_count)]
            self.initial_state = standard_initial_network_state(
                node_count, client_ids
            )
        self.initial_checkpoint_value = b""

        self.clients = {}
        # Requests submitted at the current instant, awaiting the batched
        # per-node propose flush (_flush_proposes).
        self._pending_proposes: list = []
        # (client_id, req_no) -> [pb.Reconfiguration]: the deterministic
        # app-level reconfig model — when that request commits at a node,
        # the node's app reports the reconfigurations with its next
        # checkpoint (every correct node commits the same batches, so all
        # report identically).
        self.reconfig_on_commit: dict = {}

        self.event_count = 0
        # Proposal deliveries the signature plane refused at ingress —
        # in-flight corruptions/forgeries.  The chaos corruption invariant
        # asserts this equals the adversary's rewrite count (signed mode
        # rejects 100%); mirrored to mirbft_byzantine_rejections_total
        # when hooks are enabled.
        self.byzantine_rejections = 0
        # Incremental mirror of per-node distinct-committed counts (the
        # drain predicates run every step; recounting the per-client sets
        # each time dominated large-run profiles).
        self._committed_counts: dict[int, int] = dict.fromkeys(
            range(node_count), 0
        )
        # Set whenever commitment state could have changed; drain_clients
        # only re-evaluates fully_committed() when it is — the predicate is
        # O(nodes) and running it every step dominated large-run profiles.
        self._progress = True
        self._total_reqs_cache: int | None = None
        # record=False skips the in-memory recorded_events list (an
        # interceptor still sees every event) — pod-scale runs are tens of
        # millions of events and the list dominates memory.
        self.record = record
        self.recorded_events: list = []  # [(time, node, pb.StateEvent)]
        self._queue: list = []  # heap of (time, seq, node, StateEvent)
        self._seq = 0
        self.now = 0

        self.machines: dict[int, StateMachine] = {}
        self.node_states: dict[int, NodeState] = {}
        # Deferred nodes are part of the simulated universe but not yet
        # provisioned (they join later via a node-set reconfiguration +
        # provision_node); until then they behave like crashed nodes.
        self.deferred_nodes = set(deferred_nodes)
        for node in range(node_count):
            if node in self.deferred_nodes:
                state = NodeState()
                state.crashed = True
                self.node_states[node] = state
                self.machines[node] = StateMachine()
                continue
            self._start_node(node, at_time=0)
            self._schedule(self.params.tick_interval, node, _tick_event())

        # Clients submit their initial window of requests to every node —
        # one batched delivery per node for the whole initial wave.
        for cid in client_ids:
            self.add_client(cid, reqs_per_client)
        self._flush_proposes()

    # -- bootstrap -----------------------------------------------------------

    def _start_node(self, node: int, at_time: int) -> None:
        """(Re)start a node: Initialize, replay its WAL model (or synthesize
        the bootstrap log, reference: mirbft.go:162-190), replay uncommitted
        requests, CompleteInitialization."""
        self.machines[node] = StateMachine()
        state = self.node_states.get(node)
        if state is None:
            state = NodeState()
            self.node_states[node] = state
        state.crashed = False
        state.pending = act.Actions()
        state.process_scheduled = False

        my_params = pb.InitialParameters(
            id=node,
            batch_size=self.batch_size,
            heartbeat_ticks=2,
            suspect_ticks=4,
            new_epoch_timeout_ticks=8,
            buffer_size=5 * 1024 * 1024,
        )

        events = [pb.StateEvent(type=pb.EventInitialize(initial_parms=my_params))]
        if not state.wal:
            state.wal = [
                (
                    1,
                    pb.Persistent(
                        type=pb.CEntry(
                            seq_no=0,
                            checkpoint_value=self.initial_checkpoint_value,
                            network_state=self.initial_state,
                        )
                    ),
                ),
                (
                    2,
                    pb.Persistent(
                        type=pb.FEntry(
                            ends_epoch_config=pb.EpochConfig(
                                number=0,
                                leaders=self.initial_state.config.nodes,
                            )
                        )
                    ),
                ),
            ]
        for index, entry in state.wal:
            events.append(
                pb.StateEvent(type=pb.EventLoadEntry(index=index, data=entry))
            )
        for digest, (ack, _data) in sorted(state.reqstore.items()):
            events.append(
                pb.StateEvent(type=pb.EventLoadRequest(request_ack=ack))
            )
        events.append(pb.StateEvent(type=pb.EventCompleteInitialization()))

        for event in events:
            # Boot lifecycle events bypass manglers (and the crashed-node
            # filter): they are harness machinery, not network traffic — a
            # node-scoped drop/jitter mangler must not break the strict
            # Initialize→Load→Complete sequence.
            heapq.heappush(self._queue, (at_time, self._seq, node, event))
            self._seq += 1

    # -- scheduling ----------------------------------------------------------

    def _schedule(self, delay: int, node: int, event: pb.StateEvent) -> None:
        state = self.node_states.get(node)
        if state is not None and state.crashed:
            return  # a down node loses its inbound traffic
        when = self.now + delay
        if not self.manglers:  # hot path: most runs are fault-free
            heapq.heappush(self._queue, (when, self._seq, node, event))
            self._seq += 1
            return
        # Mangler protocol: each mangler maps one candidate to None (drop),
        # a (when, node, event) tuple, or a list of tuples (duplication);
        # manglers fold left over the candidate set.
        for w, n, e in self._mangle([(when, node, event)]):
            heapq.heappush(self._queue, (w, self._seq, n, e))
            self._seq += 1

    def _schedule_frame_mangled(
        self, delay: int, source: int, target: int, msgs: list
    ) -> None:
        """Fold each msg of a frame through the manglers as its own
        EventStep candidate (per-msg fault-injection semantics), then
        re-coalesce survivors that share a delivery instant into batch
        events."""
        state = self.node_states.get(target)
        if state is not None and state.crashed:
            return  # a down node loses its inbound traffic
        when = self.now + delay
        if self.params.link_jitter:
            when += self.rng.randint(0, self.params.link_jitter)
        survivors: list = []
        for msg in msgs:
            survivors.extend(
                self._mangle(
                    [
                        (
                            when,
                            target,
                            pb.StateEvent(
                                type=pb.EventStep(source=source, msg=msg)
                            ),
                        )
                    ]
                )
            )
        merged: dict = {}
        for w, n, e in survivors:
            merged.setdefault((w, n), []).append(e)
        for (w, n), events in merged.items():
            if len(events) == 1:
                event = events[0]
            else:
                event = pb.StateEvent(
                    type=pb.EventStepBatch(
                        source=source,
                        msgs=[e.type.msg for e in events],
                    )
                )
            heapq.heappush(self._queue, (w, self._seq, n, event))
            self._seq += 1

    def provision_node(
        self, node: int, from_node: int, seq_no: int, delay: int
    ) -> None:
        """Provision a (deferred or crashed) node from another node's
        stable checkpoint and schedule its boot — the operator-side half of
        a node-set reconfiguration: the new replica starts from a snapshot
        whose network state already includes it (reference seam:
        commitstate.go:192-226; the reference admits this path 'does not
        entirely work', README.md:35 — here it is driven end to end).

        The synthesized WAL is the bootstrap pair (CEntry at the snapshot +
        FEntry for the snapshot's epoch); the app state (hash chain +
        per-client commit sets) is adopted exactly as a completed state
        transfer would."""
        source_state = self.node_states[from_node]
        stored = source_state.checkpoints.get(seq_no)
        assert stored is not None, (
            f"node {from_node} has no checkpoint at {seq_no}"
        )
        value, network_state, snapshot = stored
        assert node in network_state.config.nodes, (
            f"checkpoint at {seq_no} does not configure node {node}"
        )
        # The epoch active at the source: the new node's FEntry ends the
        # previous epoch, so its reinitialize runs the normal after-epoch-
        # change path and it integrates at the next epoch rollover.
        current = self.machines[from_node].epoch_tracker.current_epoch
        epoch_config = pb.EpochConfig(
            number=current.number,
            leaders=list(network_state.config.nodes),
            planned_expiration=0,
        )

        state = self.node_states[node]
        state.wal = [
            (
                1,
                pb.Persistent(
                    type=pb.CEntry(
                        seq_no=seq_no,
                        checkpoint_value=value,
                        network_state=network_state,
                    )
                ),
            ),
            (2, pb.Persistent(type=pb.FEntry(ends_epoch_config=epoch_config))),
        ]
        state.reqstore = {}
        state.app_chain = value
        state.last_committed = seq_no
        for cid, req_nos in snapshot.items():
            mine = self.clients[cid].committed_by_node.setdefault(node, set())
            self._committed_counts[node] += len(req_nos - mine)
            mine |= req_nos
        self._progress = True
        self.deferred_nodes.discard(node)
        self.schedule_restart(node, delay)

    def _mangle(self, candidates: list) -> list:
        """Fold candidate (when, node, event) tuples through every mangler
        (None = drop, tuple = reschedule, list = duplicate)."""
        for mangler in self.manglers:
            folded = []
            for w, n, e in candidates:
                verdict = mangler(self, w, n, e)
                if verdict is None:
                    continue
                if isinstance(verdict, list):
                    folded.extend(verdict)
                else:
                    folded.append(verdict)
            candidates = folded
        return candidates

    def schedule_restart(self, node: int, delay: int) -> None:
        """Schedule a node (possibly crashed) to boot from its durable state
        at now+delay.  Bypasses manglers and crash filtering: the restart is
        harness machinery, not network traffic."""
        heapq.heappush(
            self._queue, (self.now + delay, self._seq, node, _RESTART)
        )
        self._seq += 1

    def _submit_next_request(self, client: _ClientState) -> None:
        if client.next_req_no >= client.total_reqs:
            return
        request = client.request(client.next_req_no)
        client.next_req_no += 1
        if self.signature_plane is not None:
            self.signature_plane.submit(
                request.client_id, request.req_no, request.data
            )
        # Proposals buffer and flush as one batched delivery per node per
        # instant (see _flush_proposes) — the per-request propose fan-out
        # (reqs x nodes single events) otherwise dominates event counts.
        self._pending_proposes.append(request)

    def _flush_proposes(self) -> None:
        """Schedule everything _submit_next_request buffered at this
        instant: one EventPropose(Batch) per node at +link_latency.  Called
        at the end of __init__ (the initial client windows) and of every
        step() (window refills triggered by commits); external callers that
        submit between steps (tests) are flushed by the next step."""
        pending = self._pending_proposes
        if not pending:
            return
        self._pending_proposes = []
        delay = self.params.link_latency
        if self.manglers:
            # Per-request fault-injection semantics: each request folds
            # through the manglers as its own EventPropose candidate;
            # survivors sharing a delivery instant re-coalesce.
            for node in range(self.node_count):
                state = self.node_states.get(node)
                if state is not None and state.crashed:
                    continue
                when = self.now + delay
                survivors: list = []
                for request in pending:
                    survivors.extend(
                        self._mangle(
                            [
                                (
                                    when,
                                    node,
                                    pb.StateEvent(
                                        type=pb.EventPropose(request=request)
                                    ),
                                )
                            ]
                        )
                    )
                merged: dict = {}
                for w, n, e in survivors:
                    merged.setdefault((w, n), []).append(e)
                for (w, n), events in merged.items():
                    if len(events) == 1:
                        event = events[0]
                    else:
                        event = pb.StateEvent(
                            type=pb.EventProposeBatch(
                                requests=[e.type.request for e in events]
                            )
                        )
                    heapq.heappush(self._queue, (w, self._seq, n, event))
                    self._seq += 1
            return
        if len(pending) == 1:
            event = pb.StateEvent(type=pb.EventPropose(request=pending[0]))
        else:
            event = pb.StateEvent(type=pb.EventProposeBatch(requests=pending))
        # One shared event object for every node, like delivery frames:
        # propose events are never mutated (signature filtering builds a
        # fresh event).
        for node in range(self.node_count):
            self._schedule(delay, node, event)

    def _count_rejection(self, n: int) -> None:
        """Account n signature-plane ingress rejections (corrupted or forged
        proposal deliveries)."""
        self.byzantine_rejections += n
        if hooks.enabled:
            hooks.metrics.counter(
                "mirbft_byzantine_rejections_total", kind="corrupt"
            ).inc(n)

    # -- the loop ------------------------------------------------------------

    def step(self) -> bool:
        """Process one event; returns False when the queue is empty."""
        if not self._queue:
            return False
        when, _seq, node, event = heapq.heappop(self._queue)
        if when > self.now:
            self.now = when
            if hooks.enabled:
                # Publish the simulated clock so milestone instants carry
                # deterministic simulated time alongside wall timestamps.
                hooks.sim_now = when
            if self.hash_plane is not None:
                # Simulated time advanced: every hash submitted at earlier
                # instants is a complete wave the plane may launch now,
                # overlapping device work with the events between here and
                # the results delivery.
                self.hash_plane.on_time(when)
            if self.signature_plane is not None:
                # Same wave boundary for ingress authentication: requests
                # submitted at earlier instants may launch their verify
                # kernels now, ahead of the first delivery's valid() check.
                self.signature_plane.on_time(when)
        if event is _RESTART:
            self.restart(node)
            return True
        machine = self.machines[node]
        state = self.node_states[node]
        if state.crashed:
            return True
        if event is _PROCESS:
            # The executor pass: run everything this node accumulated since
            # the pass was scheduled.
            state.process_scheduled = False
            pending = state.pending
            state.pending = act.Actions()
            self._execute(node, state, pending)
            if self._pending_proposes:
                # Commits in this pass refilled client windows; batch the
                # new submissions into one delivery per node.
                self._flush_proposes()
            return True
        if self.signature_plane is not None:
            inner = event.type
            if isinstance(inner, pb.EventPropose):
                req = inner.request
                if not self.signature_plane.valid(
                    req.client_id, req.req_no, req.data
                ):
                    # Ingress authentication failed: the replica never
                    # steps the state machine (unrecorded, like any
                    # dropped packet).
                    self._count_rejection(1)
                    return True
            elif isinstance(inner, pb.EventProposeBatch):
                valid = self.signature_plane.valid
                reqs = [
                    r
                    for r in inner.requests
                    if valid(r.client_id, r.req_no, r.data)
                ]
                if len(reqs) != len(inner.requests):
                    self._count_rejection(len(inner.requests) - len(reqs))
                if not reqs:
                    return True
                if len(reqs) != len(inner.requests):
                    # Never mutate the shared event object; the filtered
                    # batch is what this replica (and the record) sees.
                    # Verdicts are pure functions of the bytes, so every
                    # replica filters identically.
                    event = pb.StateEvent(
                        type=pb.EventProposeBatch(requests=reqs)
                    )
        if self.mac_plane is not None:
            inner = event.type
            if isinstance(inner, pb.EventStep):
                if not self.mac_plane.admit(inner.msg):
                    # Replica-channel MAC failed: dropped at ingress,
                    # unrecorded — the live transport never delivers a
                    # bad-MAC frame to the node either.
                    return True
            elif isinstance(inner, pb.EventStepBatch):
                admit = self.mac_plane.admit
                msgs = [m for m in inner.msgs if admit(m)]
                if len(msgs) != len(inner.msgs):
                    if not msgs:
                        return True
                    # Never mutate the shared event object (other targets
                    # and the record see the original).
                    event = pb.StateEvent(
                        type=pb.EventStepBatch(
                            source=inner.source, msgs=msgs
                        )
                    )

        self.event_count += 1
        if self.hash_plane is not None:
            # Materialize lazy digests before the event is recorded or
            # applied so logs match inline execution bit-for-bit.
            self.hash_plane.resolve_event(event)
        if self.interceptor is not None:
            self.interceptor(node, self.now, event)
        if self.record:
            self.recorded_events.append((self.now, node, event))

        if isinstance(event.type, pb.EventTick):
            self._schedule(self.params.tick_interval, node, _tick_event())
        elif (
            isinstance(event.type, pb.EventTransfer)
            and event.type.c_entry.network_state is not None
        ):
            # The transferred app state is adopted when the transfer event
            # is *delivered* (not when it was scheduled — the node may have
            # crashed in between).
            self._adopt_transferred_state(node, event.type.c_entry)

        actions = machine.apply_event(event)
        if not actions.is_empty():
            state.pending.concat(actions)
            if not state.process_scheduled:
                state.process_scheduled = True
                heapq.heappush(
                    self._queue,
                    (
                        self.now + self.params.process_latency,
                        self._seq,
                        node,
                        _PROCESS,
                    ),
                )
                self._seq += 1
        if self._pending_proposes:
            # Commits in this event refilled client windows; batch the new
            # submissions into one delivery per node at this instant.
            self._flush_proposes()
        return True

    def _adopt_transferred_state(self, node: int, c_entry: pb.CEntry) -> None:
        state = self.node_states[node]
        state.app_chain = c_entry.checkpoint_value
        state.last_committed = c_entry.seq_no
        for other in range(self.node_count):
            stored = self.node_states[other].checkpoints.get(c_entry.seq_no)
            if stored is None or stored[0] != c_entry.checkpoint_value:
                continue
            snapshot = stored[2]
            for cid, req_nos in snapshot.items():
                mine = self.clients[cid].committed_by_node.setdefault(
                    node, set()
                )
                self._committed_counts[node] += len(req_nos - mine)
                mine |= req_nos
            self._progress = True
            return

    def _execute(self, node: int, state: NodeState, actions: act.Actions) -> None:
        """Model the executor: apply durable effects, schedule consequences."""
        persist_delay = 0

        for write in actions.write_ahead:
            persist_delay = self.params.persist_latency
            if write.append is not None:
                state.wal.append((write.append.index, write.append.data))
            else:
                state.wal = [
                    (i, e) for i, e in state.wal if i >= write.truncate
                ]

        for fr in actions.store_requests:
            state.reqstore[fr.request_ack.digest] = (
                fr.request_ack,
                fr.request_data,
            )

        send_delay = persist_delay + self.params.link_latency
        # Coalesce this pass's sends into one frame per distinct target
        # set — the transport-level batching that collapses the n^2
        # per-request ack fan-out into per-(source,target) deliveries.
        # All targets of a group share one event object.  A target
        # appearing in several groups receives the groups as separate
        # frames in emission order; relative reordering of msgs across
        # groups is fine (the network is unordered by assumption) and
        # deterministic (insertion-ordered dicts).
        groups: dict[tuple, list] = {}
        observe = (
            self.checkpoint_certs.observe
            if self.checkpoint_certs is not None
            else None
        )
        seal = self.mac_plane.seal if self.mac_plane is not None else None
        last_targets = None  # sends overwhelmingly share one list object
        last_key = None
        for send in actions.sends:
            if observe is not None:
                observe(node, send.msg)
            if seal is not None:
                seal(send.msg)
            targets = send.targets
            if targets is last_targets:
                key = last_key
            else:
                key = tuple(targets)
                last_targets, last_key = targets, key
            frame = groups.get(key)
            if frame is None:
                groups[key] = [send.msg]
            else:
                frame.append(send.msg)
        for fwd in actions.forward_requests:
            stored = state.reqstore.get(fwd.request_ack.digest)
            if stored is None:
                continue
            _ack, data = stored
            msg = pb.Msg(
                type=pb.ForwardRequest(
                    request_ack=fwd.request_ack, request_data=data
                )
            )
            if seal is not None:
                seal(msg)
            key = tuple(fwd.targets)
            frame = groups.get(key)
            if frame is None:
                groups[key] = [msg]
            else:
                frame.append(msg)
        if self.manglers:
            # Manglers keep their per-msg semantics: each inner msg folds
            # through the rules as its own EventStep candidate (so
            # msg-type/percent matchers behave exactly as before), and the
            # survivors that still share a delivery instant re-coalesce
            # into frames.
            for targets, msgs in groups.items():
                for target in targets:
                    self._schedule_frame_mangled(
                        send_delay, node, target, msgs
                    )
        else:
            jitter = self.params.link_jitter
            rand = self.rng.randint
            for targets, msgs in groups.items():
                if len(msgs) == 1:
                    event = pb.StateEvent(
                        type=pb.EventStep(source=node, msg=msgs[0])
                    )
                else:
                    event = pb.StateEvent(
                        type=pb.EventStepBatch(source=node, msgs=msgs)
                    )
                if jitter:
                    for target in targets:
                        self._schedule(
                            send_delay + rand(0, jitter), target, event
                        )
                else:
                    for target in targets:
                        self._schedule(send_delay, target, event)

        results = act.ActionResults()
        if actions.hashes:
            if self.hash_plane is not None:
                digests = self.hash_plane.submit(
                    [hr.data for hr in actions.hashes]
                )
            elif self.hash_executor is not None:
                digests = self.hash_executor([hr.data for hr in actions.hashes])
            else:
                digests = [host_digest(hr.data) for hr in actions.hashes]
            for hr, digest in zip(actions.hashes, digests, strict=True):
                results.digests.append(act.HashResult(digest=digest, request=hr))

        for commit in actions.commits:
            if commit.batch is not None:
                self._apply_batch(node, state, commit.batch)
            else:
                cp = commit.checkpoint
                value = state.app_chain
                reconfigs = state.pending_reconfigs
                state.pending_reconfigs = []
                # Snapshot the app state (chain + per-client commits) so a
                # lagging node can adopt it wholesale via state transfer.
                snapshot = {
                    cid: set(c.committed_by_node.get(node, ()))
                    for cid, c in self.clients.items()
                }
                state.checkpoints[cp.seq_no] = (
                    value,
                    pb.NetworkState(
                        config=cp.network_config,
                        clients=cp.clients_state,
                    ),
                    snapshot,
                )
                results.checkpoints.append(
                    act.CheckpointResult(
                        checkpoint=cp,
                        value=value,
                        reconfigurations=reconfigs,
                    )
                )

        if results.digests or results.checkpoints:
            self._schedule(
                self.params.ready_latency,
                node,
                pb.StateEvent(type=act.results_to_event(results)),
            )

        if actions.state_transfer is not None:
            self._serve_state_transfer(node, actions.state_transfer)

    def add_client(self, client_id: int, total_reqs: int) -> None:
        """Register a (reconfiguration-added) client and submit its initial
        request window to every node."""
        client = _ClientState(client_id, total_reqs=total_reqs, owner=self)
        self.clients[client_id] = client
        self._total_reqs_cache = None
        self._progress = True
        for _ in range(min(total_reqs, 100)):
            self._submit_next_request(client)

    def _apply_batch(self, node: int, state: NodeState, batch: pb.QEntry) -> None:
        if batch.seq_no <= state.last_committed:
            # A restarted state machine replays from its last stable
            # checkpoint and re-emits commits the durable app already
            # applied before the crash (reference contract: the app owns
            # commit idempotency, processor.go's persisted last-applied).
            # Re-applying would double-hash the app chain and fork the
            # node's next checkpoint off the network.
            return
        state.last_committed = batch.seq_no
        if hooks.enabled:
            hooks.milestone("seq.committed", node, batch.seq_no)
        for ack in batch.requests:
            triggered = self.reconfig_on_commit.get((ack.client_id, ack.req_no))
            if triggered:
                state.pending_reconfigs.extend(triggered)
            h = hashlib.sha256()
            h.update(state.app_chain)
            h.update(ack.digest)
            state.app_chain = h.digest()
            state.committed_reqs.append((ack.client_id, ack.req_no, batch.seq_no))
            client = self.clients.get(ack.client_id)
            if client is not None:
                seen = client.committed_by_node.setdefault(node, set())
                if ack.req_no not in seen:
                    seen.add(ack.req_no)
                    self._committed_counts[node] += 1
                    self._progress = True
                if ack.req_no not in client.committed_anywhere:
                    # First commit anywhere slides the client's submission
                    # window (a deterministic stand-in for client waiters).
                    client.committed_anywhere.add(ack.req_no)
                    self._submit_next_request(client)

    def _serve_state_transfer(self, node: int, target: act.StateTarget) -> None:
        for other in range(self.node_count):
            stored = self.node_states[other].checkpoints.get(target.seq_no)
            if stored is None:
                continue
            value, network_state, _snapshot = stored
            if value != target.value:
                continue
            # State adoption happens at delivery time (step()); here we only
            # schedule the transfer's arrival.
            self._schedule(
                self.params.state_transfer_latency,
                node,
                pb.StateEvent(
                    type=pb.EventTransfer(
                        c_entry=pb.CEntry(
                            seq_no=target.seq_no,
                            checkpoint_value=value,
                            network_state=network_state,
                        )
                    )
                ),
            )
            return
        # Nobody has it yet; retry after a delay by re-scheduling the check.
        self._schedule(
            self.params.state_transfer_latency,
            node,
            pb.StateEvent(
                type=pb.EventTransfer(
                    c_entry=pb.CEntry(
                        seq_no=target.seq_no,
                        checkpoint_value=target.value,
                        network_state=None,  # signals failure → retry
                    )
                )
            ),
        )

    # -- crash / restart (used by manglers) ----------------------------------

    def crash(self, node: int) -> None:
        self.node_states[node].crashed = True
        self._progress = True  # a crashed node leaves the commitment quorum
        self._queue = [
            entry
            for entry in self._queue
            if entry[2] != node or entry[3] is _RESTART
        ]
        heapq.heapify(self._queue)

    def restart(self, node: int) -> None:
        self._start_node(node, at_time=self.now)
        self._schedule(self.params.tick_interval, node, _tick_event())

    # -- assertions ----------------------------------------------------------

    @property
    def _total_reqs(self) -> int:
        if self._total_reqs_cache is None:
            self._total_reqs_cache = sum(
                c.total_reqs for c in self.clients.values()
            )
        return self._total_reqs_cache

    def set_client_total(self, client_id: int, total_reqs: int) -> None:
        """Adjust how many requests a client will submit (e.g. a test
        shortening a removed client's stream).  Equivalent to assigning
        ``clients[cid].total_reqs`` — the setter invalidates the cache."""
        self.clients[client_id].total_reqs = total_reqs

    def fully_committed(self) -> bool:
        total = self._total_reqs
        if total == 0:
            return True
        return all(
            self._committed_counts[n] >= total
            for n in range(self.node_count)
            if not self.node_states[n].crashed
        )

    def drain_until(self, predicate, max_steps: int = 100_000) -> int:
        """Run until predicate(self) holds; returns events processed."""
        with _gc_paused():
            for _ in range(max_steps):
                if predicate(self):
                    return self.event_count
                if not self.step():
                    raise AssertionError(
                        f"event queue drained before condition "
                        f"({self.event_count} events)"
                    )
        raise AssertionError(
            f"condition not reached after {max_steps} steps "
            f"({self.event_count} events)"
        )

    def committed_at(self, node: int) -> int:
        """Distinct requests committed (or adopted via transfer) at node."""
        return self._committed_counts[node]

    def drain_clients(self, max_steps: int = 100_000) -> int:
        """Run until every client's requests commit at every live node;
        returns the number of events processed (the determinism anchor)."""
        check = True  # always evaluate on entry (drain may be a no-op)
        with _gc_paused():
            for _ in range(max_steps):
                if check or self._progress:
                    check = False
                    self._progress = False
                    if self.fully_committed():
                        return self.event_count
                if not self.step():
                    raise AssertionError(
                        f"event queue drained before full commitment "
                        f"({self.event_count} events)"
                    )
        raise AssertionError(
            f"no full commitment after {max_steps} steps "
            f"({self.event_count} events)"
        )


class _RestartSentinel:
    """Queue marker: boot this node when popped (sorts after real events at
    the same (when, seq) because it is never compared — seq breaks ties)."""

    def __repr__(self):
        return "<restart>"


_RESTART = _RestartSentinel()


class _ProcessSentinel:
    """Queue marker: run the node's executor pass over its accumulated
    Actions.  Harness machinery like _RESTART: not a StateEvent, never
    recorded, never counted, never mangled."""

    def __repr__(self):
        return "<process>"


_PROCESS = _ProcessSentinel()


def _tick_event() -> pb.StateEvent:
    return pb.StateEvent(type=pb.EventTick())


def BasicRecorder(
    node_count: int, client_count: int, reqs_per_client: int, **kwargs
) -> Recorder:
    """The standard fixture (reference: testengine/recorder.go:637-685)."""
    return Recorder(node_count, client_count, reqs_per_client, **kwargs)
