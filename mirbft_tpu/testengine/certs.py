"""Checkpoint quorum certificates: BLS multi-signatures over stability.

BASELINE ladder rung 4's protocol integration: every Checkpoint message a
replica broadcasts doubles as a BLS vote over the statement
(seq_no, checkpoint value).  When 2f+1 replicas have announced the same
statement, the certificate plane aggregates their G1 signatures — on the
accelerator, in batch (ops/bls_g1.py) — into one constant-size quorum
certificate that any external verifier checks with a single pairing
equation (crypto/bls_host.py), no transcript of 2f+1 messages needed.

This is consumer-side machinery riding the engine's executor (the
reference leaves proofs-of-stability to the application layer entirely);
determinism is untouched because certificates are derived from, and feed
nothing back into, the event stream.
"""

from __future__ import annotations

from .. import pb
from ..crypto import qc


def node_seed(node_id: int) -> bytes:
    return b"mirbft-tpu-bls-node" + node_id.to_bytes(13, "big")


def statement(seq_no: int, value: bytes) -> bytes:
    return b"checkpoint %d " % seq_no + value


class CheckpointCertPlane:
    """Collects checkpoint votes from the engine's send stream and turns
    quorums into aggregated certificates.

    Install via ``Recorder(checkpoint_certs=plane)``; the engine calls
    ``observe`` for every Checkpoint broadcast.  Aggregation is deferred:
    pending quorums accumulate and aggregate as one device batch when
    ``certificates()`` is called (or a cert is first read), the same
    coalescing pattern as the digest plane."""

    def __init__(self, quorum: int, use_device: bool = True):
        self.quorum = quorum
        self.use_device = use_device
        # (seq_no, value) -> {node_id: G1 signature point}
        self._votes: dict = {}
        self._pending: list = []  # quorum-reached keys awaiting aggregation
        self._certs: dict = {}  # (seq_no, value) -> (sorted signers, asig)

    def observe(self, node_id: int, msg: pb.Msg) -> None:
        inner = msg.type
        if not isinstance(inner, pb.Checkpoint):
            return
        key = (inner.seq_no, inner.value)
        votes = self._votes.setdefault(key, {})
        if node_id in votes:
            return  # retransmission
        if key in self._certs or len(votes) >= self.quorum:
            # The certificate is already settled (or pending): don't pay a
            # scalar multiplication for a vote that can never be used.
            return
        votes[node_id] = qc.sign_vote(
            node_seed(node_id), statement(inner.seq_no, inner.value)
        )
        if len(votes) == self.quorum:
            self._pending.append(key)

    def _aggregate_pending(self) -> None:
        if not self._pending:
            return
        keys = self._pending
        self._pending = []
        certs = [
            [sig for _node, sig in sorted(self._votes[key].items())][
                : self.quorum
            ]
            for key in keys
        ]
        if self.use_device:
            from ..ops.bls_g1 import aggregate_signatures

            aggregated = aggregate_signatures(certs)
        else:
            aggregated = [qc.aggregate(c, use_device=False) for c in certs]
        for key, asig in zip(keys, aggregated):
            signers = sorted(self._votes[key])[: self.quorum]
            self._certs[key] = (signers, asig)

    def certificates(self) -> dict:
        """(seq_no, value) -> (signer ids, aggregate G1 signature)."""
        self._aggregate_pending()
        return dict(self._certs)

    @staticmethod
    def verify(seq_no: int, value: bytes, signers, asig) -> bool:
        """External check: one pairing equation against the signer set's
        aggregate public key (crypto/qc.py counts the outcome)."""
        pks = [qc.public_key(node_seed(n)) for n in signers]
        return qc.verify_cert(pks, statement(seq_no, value), asig)
