"""Signed-request support for the testengine (BASELINE ladder rung 3).

The reference explicitly leaves request authentication to the consumer —
``Node.Step`` documents that the caller must have authenticated the source
(reference: mirbft.go:297-301, docs/Design.md:18-21).  This module is that
consumer-side ingress authentication, TPU-native: clients Ed25519-sign
their requests, and replicas verify them in deferred batches through the
same coalescing-plane pattern as digesting (crypto_plane.py).

Request wire format in signed mode::

    data = payload || signature(64) || public_key(32)

The signed message binds client identity and sequence position:
``b"%d:%d:" % (client_id, req_no) + payload`` — a replayed signature for a
different (client, req_no) fails verification.
"""

from __future__ import annotations

from ..crypto import ed25519_host as host
from ..obsv import hooks
from ..resilience import CircuitBreaker
from .crypto_plane import DevicePlaneError

SIG_LEN = 64
PK_LEN = 32
TRAILER = SIG_LEN + PK_LEN


def client_seed(client_id: int) -> bytes:
    """Deterministic per-client signing seed (test harness only)."""
    return b"mirbft-tpu-client" + client_id.to_bytes(15, "big")


def signing_message(client_id: int, req_no: int, payload: bytes) -> bytes:
    return b"%d:%d:" % (client_id, req_no) + payload


def make_signer():
    """Returns signer(client_id, req_no, payload) -> signed request data.
    Public keys are derived (and cached) from the deterministic seeds."""
    pk_cache: dict[int, bytes] = {}

    def signer(client_id: int, req_no: int, payload: bytes) -> bytes:
        seed = client_seed(client_id)
        pk = pk_cache.get(client_id)
        if pk is None:
            pk = pk_cache[client_id] = host.public_key(seed)
        sig = host.sign(seed, signing_message(client_id, req_no, payload))
        return payload + sig + pk

    return signer


def split_signed(data: bytes):
    """data -> (payload, signature, public key); None if malformed."""
    if len(data) < TRAILER:
        return None
    return data[:-TRAILER], data[-TRAILER:-PK_LEN], data[-PK_LEN:]


# Expected-key registry, cached at module scope: derivation is a
# milliseconds-long pure-Python scalar mult and the keys are deterministic
# per client id, so re-deriving them on every SignaturePlane flush would
# dominate signed-run time.
_PK_CACHE: dict[int, bytes] = {}


def register_pk(client_id: int, pk: bytes) -> None:
    """Pre-populate the expected-key registry.  A real deployment receives
    client public keys as configuration (like the network state); deriving
    them from seeds at first use is harness convenience — client setup
    (e.g. the pre-signing pass) should register keys so replica-side
    verification never pays the derivation."""
    _PK_CACHE[client_id] = pk


def _expected_pk(client_id: int, cache: dict = _PK_CACHE) -> bytes:
    pk = cache.get(client_id)
    if pk is None:
        pk = cache[client_id] = host.public_key(client_seed(client_id))
    return pk


def host_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], via the host oracle."""
    cache = _PK_CACHE
    out = []
    for client_id, req_no, data in items:
        parts = split_signed(data)
        if parts is None:
            out.append(False)
            continue
        payload, sig, pk = parts
        out.append(
            pk == _expected_pk(client_id, cache)
            and host.verify(
                pk, signing_message(client_id, req_no, payload), sig
            )
        )
    return out


def kernel_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], signatures batched
    onto the accelerator (ops.ed25519.verify_batch); the client-identity
    binding (pk == registry pk) stays host-side."""
    from ..ops.ed25519 import verify_batch

    cache = _PK_CACHE
    out = [False] * len(items)
    pks, msgs, sigs, slots = [], [], [], []
    for slot, (client_id, req_no, data) in enumerate(items):
        parts = split_signed(data)
        if parts is None:
            continue
        payload, sig, pk = parts
        if pk != _expected_pk(client_id, cache):
            continue
        pks.append(pk)
        msgs.append(signing_message(client_id, req_no, payload))
        sigs.append(sig)
        slots.append(slot)
    if slots:
        for slot, valid in zip(slots, verify_batch(pks, msgs, sigs)):
            out[slot] = bool(valid)
    return out


def pallas_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], via the full Pallas
    pipeline (device point decompression + windowed ladder,
    ops.ed25519_pallas.verify_batch_pallas); the client-identity binding
    (pk == registry pk) stays host-side."""
    from ..ops.ed25519_pallas import verify_batch_pallas

    cache = _PK_CACHE
    out = [False] * len(items)
    pks, msgs, sigs, slots = [], [], [], []
    for slot, (client_id, req_no, data) in enumerate(items):
        parts = split_signed(data)
        if parts is None:
            continue
        payload, sig, pk = parts
        if pk != _expected_pk(client_id, cache):
            continue
        pks.append(pk)
        msgs.append(signing_message(client_id, req_no, payload))
        sigs.append(sig)
        slots.append(slot)
    if slots:
        for slot, valid in zip(slots, verify_batch_pallas(pks, msgs, sigs)):
            out[slot] = bool(valid)
    return out


def rlc_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], via the host batch
    authority (crypto.ed25519_batch random-linear-combination): one
    multi-scalar multiplication per chunk instead of two scalar mults per
    signature.  Verdicts match host_verifier bit-for-bit (the descent
    leaves decide with the exact oracle equation); the client-identity
    binding (pk == registry pk) stays per-item."""
    from ..crypto import ed25519_batch

    cache = _PK_CACHE
    out = [False] * len(items)
    triples, slots = [], []
    for slot, (client_id, req_no, data) in enumerate(items):
        parts = split_signed(data)
        if parts is None:
            continue
        payload, sig, pk = parts
        if pk != _expected_pk(client_id, cache):
            continue
        triples.append((pk, signing_message(client_id, req_no, payload), sig))
        slots.append(slot)
    if slots:
        for slot, valid in zip(slots, ed25519_batch.verify_batch(triples)):
            out[slot] = bool(valid)
    return out


def kernel_authority() -> bool:
    """The device/host verify authority contract (docs/CRYPTO.md): the
    accelerator batch kernel holds verification authority only when a
    real device backend is attached; CPU hosts use the host batch
    authority (RLC), never XLA-on-CPU."""
    global _KERNEL_AUTHORITY
    if _KERNEL_AUTHORITY is None:
        try:
            import jax

            _KERNEL_AUTHORITY = jax.default_backend() in ("tpu", "gpu")
        except Exception:
            _KERNEL_AUTHORITY = False
    return _KERNEL_AUTHORITY


_KERNEL_AUTHORITY: bool | None = None


def batch_verifier():
    """The batch verifier holding authority on this host — what live
    embedders inject into runtime/ingress.SpeculativeIngress (runtime/
    itself never imports crypto; see W21)."""
    return kernel_verifier if kernel_authority() else rlc_verifier


class SignaturePlane:
    """Deferred, coalesced request authentication.

    Requests are submitted at schedule time (the client broadcast) and
    judged at first delivery — at which point everything pending verifies
    as one batch.  Verdicts are cached by (client_id, req_no, data), so
    each distinct request is verified exactly once no matter how many
    replicas receive it.  Deterministic: verdicts depend only on the data.
    """

    def __init__(self, verifier=host_verifier, breaker=None, timeout_s=None):
        self.verifier = verifier
        # Same degradation policy as the digest plane: a verifier batch
        # that raises, short-reads, or times out recomputes on the host
        # oracle, and the breaker decides when to stop trying the device.
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.timeout_s = timeout_s
        self.device_errors = 0
        self.fallback_verifies = 0
        self._pending: list = []  # [(client_id, req_no, data)]
        self._verdicts: dict = {}
        self.flush_sizes: list[int] = []
        # Blocking wall time per flush — the ingress-auth latency the
        # replica actually experiences (the bench's rung-3 verify p99).
        self.flush_wall_s: list[float] = []

    def _guarded_verify(self, batch: list) -> list:
        if not self.breaker.allow():
            self.fallback_verifies += len(batch)
            return host_verifier(batch)
        import time

        start = time.perf_counter()
        try:
            verdicts = self.verifier(batch)
            if len(verdicts) != len(batch):
                raise DevicePlaneError(
                    f"short read: {len(verdicts)} of {len(batch)} verdicts"
                )
        except Exception:
            self.breaker.record_failure()
            self.device_errors += 1
            self.fallback_verifies += len(batch)
            return host_verifier(batch)
        if (
            self.timeout_s is not None
            and time.perf_counter() - start > self.timeout_s
        ):
            self.breaker.record_failure()
        else:
            self.breaker.record_success()
        return verdicts

    def _key(self, client_id: int, req_no: int, data: bytes):
        return (client_id, req_no, data)

    def submit(self, client_id: int, req_no: int, data: bytes) -> None:
        key = self._key(client_id, req_no, data)
        if key not in self._verdicts:
            self._pending.append((client_id, req_no, data))
            self._verdicts[key] = None  # reserved: pending

    def on_time(self, _now: int) -> None:
        """Engine hook at simulated-time advancement; the base plane stays
        fully lazy (AsyncSignaturePlane launches completed waves here)."""

    def valid(self, client_id: int, req_no: int, data: bytes) -> bool:
        key = self._key(client_id, req_no, data)
        verdict = self._verdicts.get(key)
        if verdict is None:
            self.submit(client_id, req_no, data)  # no-op if already pending
            self._flush()
            verdict = self._verdicts[key]
        return verdict

    def _flush(self) -> None:
        if not self._pending:
            return
        import time

        batch = self._pending
        self._pending = []
        self.flush_sizes.append(len(batch))
        start = time.perf_counter()
        verdicts = self._guarded_verify(batch)
        wall = time.perf_counter() - start
        self.flush_wall_s.append(wall)
        if hooks.enabled:
            hooks.record_flush("signature", "batch", len(batch), wall)
        for item, verdict in zip(batch, verdicts, strict=True):
            self._verdicts[self._key(*item)] = verdict


class AsyncSignaturePlane(SignaturePlane):
    """The accelerator-backed signature plane, tuned the way the digest
    plane was in round 4 (crypto_plane.AsyncKernelHashPlane):

    - **Cheap rejection at submit time.**  Structural parsing and the
      client-identity binding (pk == registry pk) run at submit; a request
      that fails either never reaches a kernel.
    - **Proactive launching.**  Marshalled rows accumulate into a wave;
      when simulated time advances past the submission instant (the
      engine's ``on_time`` hook) — or the wave reaches ``chunk`` rows — the
      wave dispatches to the device verify pipeline asynchronously.  The
      ladder kernel then runs while the engine chews through the events
      between submission and the first delivery (``link_latency`` later),
      so ``valid()`` usually finds the verdict round trip already done.
    - **Host verification only for sub-tile stragglers.**  Unlike digests
      (host hashlib is µs), a host Ed25519 verify is ~5ms of pure Python —
      so a demanded in-flight chunk *blocks on the device* rather than
      recomputing, and only waves too small to be worth a padded-tile
      launch (< ``min_device_rows`` at a wave boundary, or demanded before
      one) fall back to the host oracle.

    Verdicts depend only on the request bytes, so determinism, event
    counts, and chains are identical to the synchronous plane.
    """

    def __init__(
        self,
        chunk: int = 1024,
        sublanes: int = 8,
        min_device_rows: int = 16,
        launch_fn=None,
        breaker=None,
        timeout_s=None,
        max_outstanding: int = 8,
        stale_boundaries: int = 2,
    ):
        # Default chunk/sublanes: 1024-row launches on the 8x128 tile.
        # A monolithic wave would make the FIRST forced readback wait for
        # the whole kernel; 1024-row pieces queue back-to-back on device,
        # so the first force blocks ~one piece (<100ms) and later pieces
        # are ready long before the engine works through the deliveries
        # standing between it and them.
        #
        # min_device_rows=16 ~ the host/device break-even: a host verify
        # is ~5ms of pure Python per row (always blocking), a padded-tile
        # launch is ~65ms of device time that overlaps the event loop.
        #
        # Deliberately NOT calling super().__init__: the base plane's
        # verifier/_pending machinery is replaced wholesale by the
        # wave/chunk state below; only the verdict cache and flush
        # telemetry are shared contract.
        self._verdicts = {}
        self.flush_sizes = []
        self.flush_wall_s = []
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.timeout_s = timeout_s
        self.device_errors = 0
        self.fallback_verifies = 0
        self.chunk = chunk
        self.sublanes = sublanes
        self.min_device_rows = min_device_rows
        # launch_fn(rows, sublanes) -> in-flight device verdict array;
        # pluggable so CPU-only tests can use the XLA scan pipeline (the
        # Pallas default needs a real TPU).
        self._launch_fn = launch_fn
        self._wave: list = []  # [(key, marshal_light row, pk, msg, sig)]
        # cid -> (wave entries, out, launch_s, born_boundary); the full
        # entries (not just keys) are retained so a failed readback can
        # host-rescue from the (pk, msg, sig) material without
        # re-marshalling.
        self._chunks: dict = {}
        self._chunk_of: dict = {}  # key -> cid
        self._next_chunk = 0
        self._dirty = False
        # Bounded-outstanding discipline: under manglers a request can be
        # submitted (and its chunk launched) yet never demanded — drops,
        # redirects, and crashed recipients mean valid() never fires for
        # its key, so without retirement _chunks/_chunk_of grow for the
        # whole run.  Two bounds keep them finite:
        #   - max_outstanding caps live chunks; launching past the cap
        #     forces the oldest chunk's readback first.
        #   - a chunk still undemanded stale_boundaries wave boundaries
        #     after launch is force-read at on_time (its kernel finished
        #     long ago, so the readback is a near-free drain).
        self.max_outstanding = max_outstanding
        self.stale_boundaries = stale_boundaries
        self._boundary = 0  # on_time wave-boundary counter
        self.forced_retirements = 0
        # Telemetry (bench): launches overlapped with the event loop,
        # device/host verdict split, demanded-before-ready blocks.
        self.overlapped_launches = 0
        self.device_verifies = 0
        self.host_verifies = 0

    def submit(self, client_id: int, req_no: int, data: bytes) -> None:
        key = self._key(client_id, req_no, data)
        if key in self._verdicts:
            return
        parts = split_signed(data)
        if parts is None:
            self._verdicts[key] = False
            return
        payload, sig, pk = parts
        if pk != _expected_pk(client_id):
            self._verdicts[key] = False
            return
        from ..ops.ed25519_pallas import marshal_light

        msg = signing_message(client_id, req_no, payload)
        row = marshal_light(pk, msg, sig)
        if row is None:
            self._verdicts[key] = False
            return
        self._verdicts[key] = None  # pending
        self._wave.append((key, row, pk, msg, sig))
        self._dirty = True
        if len(self._wave) >= self.chunk:
            self._launch()

    def on_time(self, _now: int) -> None:
        self._boundary += 1
        # Force-or-free stale chunks: anything launched stale_boundaries
        # wave boundaries ago and still undemanded gets its verdicts read
        # back now, freeing the retained wave material and the _chunk_of
        # index entries (the verdict cache itself is the plane's contract).
        floor = self._boundary - self.stale_boundaries
        stale = [
            cid
            for cid, entry in self._chunks.items()
            if entry[3] <= floor
        ]
        for cid in stale:
            self.forced_retirements += 1
            self._retire(cid)
        if self._dirty:
            self._dirty = False
            if len(self._wave) >= self.min_device_rows:
                self._launch()

    def _launch(self) -> None:
        import time

        if self._launch_fn is None:
            from ..ops.ed25519_pallas import launch_rows

            self._launch_fn = launch_rows
        wave, self._wave = self._wave, []
        if not self.breaker.allow():
            self._host_verify_wave(wave)
            self.fallback_verifies += len(wave)
            return
        start = time.perf_counter()
        try:
            out = self._launch_fn(
                [row for _k, row, _pk, _m, _s in wave],
                sublanes=self.sublanes,
            )
        except Exception:
            self.breaker.record_failure()
            self.device_errors += 1
            self.fallback_verifies += len(wave)
            self._host_verify_wave(wave)
            return
        launch_s = time.perf_counter() - start
        cid = self._next_chunk
        self._next_chunk += 1
        self._chunks[cid] = (wave, out, launch_s, self._boundary)
        for k, _row, _pk, _m, _s in wave:
            self._chunk_of[k] = cid
        # Cap outstanding chunks: retire the oldest (its kernel queued
        # first, so it is the most likely to be done) before the map can
        # outgrow max_outstanding.
        while len(self._chunks) > self.max_outstanding:
            oldest = min(self._chunks)
            self.forced_retirements += 1
            self._retire(oldest)
        self.flush_sizes.append(len(wave))
        self.overlapped_launches += 1
        self.device_verifies += len(wave)
        if hooks.enabled:
            hooks.record_flush("signature", "device", len(wave), launch_s)

    def valid(self, client_id: int, req_no: int, data: bytes) -> bool:
        key = self._key(client_id, req_no, data)
        if key not in self._verdicts:
            self.submit(client_id, req_no, data)
        verdict = self._verdicts[key]
        if verdict is not None:
            return verdict
        cid = self._chunk_of.get(key)
        if cid is None:
            self._flush()  # sub-tile wave demanded: host oracle
            return self._verdicts[key]
        return self._force(cid, key)

    def _force(self, cid: int, key) -> bool:
        self._retire(cid)
        return self._verdicts[key]

    def _retire(self, cid: int) -> None:
        """Read a chunk's verdicts back and drop its retained material."""
        import time

        import numpy as np

        wave, out, launch_s, _born = self._chunks.pop(cid)
        start = time.perf_counter()
        try:
            valid = np.asarray(out)
            if len(valid) < len(wave):
                raise DevicePlaneError(
                    f"short readback: {len(valid)} of {len(wave)} verdicts"
                )
        except Exception:
            # Device died mid-wave: rescue from the retained (pk, msg, sig)
            # material via the host oracle, and let the breaker steer the
            # next waves straight to _host_verify_wave.
            self.breaker.record_failure()
            self.device_errors += 1
            self.fallback_verifies += len(wave)
            self.device_verifies -= len(wave)
            for k, _row, _pk, _m, _s in wave:
                del self._chunk_of[k]
            self._host_verify_wave(wave)
            wall = launch_s + time.perf_counter() - start
            self.flush_wall_s.append(wall)
            if hooks.enabled:
                hooks.record_flush("signature", "rescued", len(wave), wall)
            return
        self.breaker.record_success()
        wall = launch_s + time.perf_counter() - start
        self.flush_wall_s.append(wall)
        if hooks.enabled:
            hooks.record_flush("signature", "readback", len(wave), wall)
        verdicts = self._verdicts
        chunk_of = self._chunk_of
        for i, (k, _row, _pk, _m, _s) in enumerate(wave):
            verdicts[k] = bool(valid[i])
            del chunk_of[k]

    def _host_verify_wave(self, wave: list) -> None:
        """Synchronously judge a wave's entries via the host oracle."""
        import time

        self.flush_sizes.append(len(wave))
        start = time.perf_counter()
        for key, _row, pk, msg, sig in wave:
            self._verdicts[key] = host.verify(pk, msg, sig)
        wall = time.perf_counter() - start
        self.flush_wall_s.append(wall)
        self.host_verifies += len(wave)
        if hooks.enabled:
            hooks.record_flush("signature", "host", len(wave), wall)

    def _flush(self) -> None:
        """Host-verify the pending (sub-tile) wave synchronously."""
        if not self._wave:
            return
        wave, self._wave = self._wave, []
        self._host_verify_wave(wave)

class SpeculativeSignaturePlane(SignaturePlane):
    """Speculative batched ingress verification (PR 20's tentpole leg 1).

    Mir's amortization argument: client-signature verification does not
    have to gate intake — requests may be *admitted optimistically* into
    the pre-consensus queues (the engine's delivery queue here, the
    runtime's ingress stage in `runtime/ingress.py`) while their
    signatures verify as batches off the critical path, as long as the
    verdict joins before the request can reach the ordered log.

    Mechanics on the deterministic engine:

    - ``submit`` (the client broadcast instant) performs only the cheap
      structural + client-identity admission and parks the request in the
      speculative queue — intake is never gated on curve arithmetic.
    - ``on_time`` (the simulated-time wave boundary, which fires before
      the first delivery of anything submitted at earlier instants)
      verifies the parked wave in chunk-bounded bursts: through the
      accelerator batch kernel (`ops/ed25519.py`, pow2-bucketed rows via
      ``pack_rows``) when the device holds verify authority, else through
      the host batch authority (`crypto/ed25519_batch.py`, one
      multi-scalar multiplication per burst).  Each burst's blocking wall
      time lands in ``flush_wall_s`` — the rung3 verify p99.
    - ``valid`` (the delivery join, before the replica steps the state
      machine) is then an O(1) verdict lookup; a demanded-before-boundary
      key forces the join early.  A False verdict evicts the
      speculatively-admitted request — counted here and mirrored to
      ``mirbft_crypto_speculative_evictions_total`` — so a bad-signature
      request can be *in flight* but never *ordered*, and
      ``check_corruption_rejected`` still observes 100% rejection.

    Verdicts depend only on the request bytes (and match the host oracle
    bit-for-bit), so determinism, event counts, and app chains are
    unchanged from the synchronous plane.
    """

    def __init__(
        self,
        chunk: int = 64,
        kernel_chunk: int = 512,
        breaker=None,
        timeout_s=None,
        use_kernel: bool | None = None,
    ):
        super().__init__(
            verifier=rlc_verifier, breaker=breaker, timeout_s=timeout_s
        )
        # Host-authority burst width: one RLC combined check per burst.
        # 64 keeps a burst under the 100ms ingress SLO on a commodity
        # core while amortizing the MSM over the wave.
        self.chunk = chunk
        # Device-authority burst width (pow2-padded tiles are cheap, so
        # bursts can be much wider before latency matters).
        self.kernel_chunk = kernel_chunk
        self._use_kernel = use_kernel
        self.speculative_evictions = 0
        self.forced_joins = 0
        self.admitted = 0
        self.device_verifies = 0
        self.host_verifies = 0

    # -- admission ---------------------------------------------------------

    def submit(self, client_id: int, req_no: int, data: bytes) -> None:
        key = self._key(client_id, req_no, data)
        if key in self._verdicts:
            return
        parts = split_signed(data)
        if parts is None:
            self._verdicts[key] = False
            return
        _payload, _sig, pk = parts
        if pk != _expected_pk(client_id):
            self._verdicts[key] = False
            return
        self._verdicts[key] = None  # pending: speculatively admitted
        self._pending.append((client_id, req_no, data))
        self.admitted += 1

    @property
    def speculative_depth(self) -> int:
        """Requests currently admitted but not yet judged (status.py)."""
        return len(self._pending)

    # -- the join ----------------------------------------------------------

    def on_time(self, _now: int) -> None:
        if self._pending:
            self._flush()

    def valid(self, client_id: int, req_no: int, data: bytes) -> bool:
        key = self._key(client_id, req_no, data)
        verdict = self._verdicts.get(key)
        if verdict is None:
            self.submit(client_id, req_no, data)  # no-op if already parked
            self.forced_joins += 1
            self._flush()
            verdict = self._verdicts[key]
        if not verdict:
            self.speculative_evictions += 1
            if hooks.enabled:
                hooks.metrics.counter(
                    "mirbft_crypto_speculative_evictions_total"
                ).inc()
        return verdict

    # -- burst verification ------------------------------------------------

    def _kernel_path(self) -> bool:
        if self._use_kernel is not None:
            return self._use_kernel
        return kernel_authority()

    def _flush(self) -> None:
        if not self._pending:
            return
        import time

        wave, self._pending = self._pending, []
        kernel = self._kernel_path() and self.breaker.allow()
        chunk = self.kernel_chunk if kernel else self.chunk
        verifier = kernel_verifier if kernel else rlc_verifier
        path = "device" if kernel else "rlc"
        for base in range(0, len(wave), chunk):
            burst = wave[base : base + chunk]
            start = time.perf_counter()
            try:
                verdicts = verifier(burst)
                if len(verdicts) != len(burst):
                    raise DevicePlaneError(
                        f"short read: {len(verdicts)} of {len(burst)}"
                    )
            except Exception:
                # Kernel path died: breaker steers the remaining bursts
                # (and future waves) to the host batch authority.
                self.breaker.record_failure()
                self.device_errors += 1
                self.fallback_verifies += len(burst)
                verdicts = rlc_verifier(burst)
            else:
                if kernel:
                    self.breaker.record_success()
            wall = time.perf_counter() - start
            self.flush_sizes.append(len(burst))
            self.flush_wall_s.append(wall)
            if kernel:
                self.device_verifies += len(burst)
            else:
                self.host_verifies += len(burst)
            if hooks.enabled:
                hooks.record_flush("signature", path, len(burst), wall)
            for item, verdict in zip(burst, verdicts, strict=True):
                self._verdicts[self._key(*item)] = verdict


class MacSealPlane:
    """Deterministic-engine model of MAC-authenticated replica channels
    (crypto/mac.py is the live implementation; this is its simulation
    twin, the way SignaturePlane twins the live ingress verifier).

    The model is identity-based rather than cryptographic: the engine
    seals every node-to-node message object a legitimate sender emits,
    and at delivery admits a message only if that exact object was
    sealed.  Manglers that tamper with replica traffic always *rewrite*
    (corrupt()/_restep build fresh objects, never mutate — other targets
    share the original), so a forged or tampered message is by
    construction unsealed and is dropped at ingress exactly where the
    live transport drops a bad-MAC frame.  Duplicate deliveries of a
    sealed object are admitted — PBFT-style link MACs authenticate, they
    do not prevent replay; dedup owns that (docs/CRYPTO.md).

    Scope: EventStep/EventStepBatch (the replica plane).  Client
    proposes stay signature-authenticated and state-transfer events are
    modelled at the digest layer, mirroring the live lane split.

    Sealed objects are pinned by strong reference so an id() can never
    be recycled into a false admit.  Registry size is bounded by the
    scenario's total send count — chaos-scale runs, not pod-scale ones.
    """

    def __init__(self):
        self._sealed: dict[int, object] = {}
        self.sealed = 0
        self.rejections = 0

    def seal(self, msg) -> None:
        key = id(msg)
        if key not in self._sealed:
            self._sealed[key] = msg
            self.sealed += 1

    def admit(self, msg) -> bool:
        if id(msg) in self._sealed:
            return True
        self.rejections += 1
        if hooks.enabled:
            hooks.metrics.counter(
                "mirbft_mac_rejections_total", kind="unsealed"
            ).inc()
        return False
