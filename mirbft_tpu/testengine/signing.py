"""Signed-request support for the testengine (BASELINE ladder rung 3).

The reference explicitly leaves request authentication to the consumer —
``Node.Step`` documents that the caller must have authenticated the source
(reference: mirbft.go:297-301, docs/Design.md:18-21).  This module is that
consumer-side ingress authentication, TPU-native: clients Ed25519-sign
their requests, and replicas verify them in deferred batches through the
same coalescing-plane pattern as digesting (crypto_plane.py).

Request wire format in signed mode::

    data = payload || signature(64) || public_key(32)

The signed message binds client identity and sequence position:
``b"%d:%d:" % (client_id, req_no) + payload`` — a replayed signature for a
different (client, req_no) fails verification.
"""

from __future__ import annotations

from ..crypto import ed25519_host as host

SIG_LEN = 64
PK_LEN = 32
TRAILER = SIG_LEN + PK_LEN


def client_seed(client_id: int) -> bytes:
    """Deterministic per-client signing seed (test harness only)."""
    return b"mirbft-tpu-client" + client_id.to_bytes(15, "big")


def signing_message(client_id: int, req_no: int, payload: bytes) -> bytes:
    return b"%d:%d:" % (client_id, req_no) + payload


def make_signer():
    """Returns signer(client_id, req_no, payload) -> signed request data.
    Public keys are derived (and cached) from the deterministic seeds."""
    pk_cache: dict[int, bytes] = {}

    def signer(client_id: int, req_no: int, payload: bytes) -> bytes:
        seed = client_seed(client_id)
        pk = pk_cache.get(client_id)
        if pk is None:
            pk = pk_cache[client_id] = host.public_key(seed)
        sig = host.sign(seed, signing_message(client_id, req_no, payload))
        return payload + sig + pk

    return signer


def split_signed(data: bytes):
    """data -> (payload, signature, public key); None if malformed."""
    if len(data) < TRAILER:
        return None
    return data[:-TRAILER], data[-TRAILER:-PK_LEN], data[-PK_LEN:]


# Expected-key registry, cached at module scope: derivation is a
# milliseconds-long pure-Python scalar mult and the keys are deterministic
# per client id, so re-deriving them on every SignaturePlane flush would
# dominate signed-run time.
_PK_CACHE: dict[int, bytes] = {}


def _expected_pk(client_id: int, cache: dict = _PK_CACHE) -> bytes:
    pk = cache.get(client_id)
    if pk is None:
        pk = cache[client_id] = host.public_key(client_seed(client_id))
    return pk


def host_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], via the host oracle."""
    cache = _PK_CACHE
    out = []
    for client_id, req_no, data in items:
        parts = split_signed(data)
        if parts is None:
            out.append(False)
            continue
        payload, sig, pk = parts
        out.append(
            pk == _expected_pk(client_id, cache)
            and host.verify(
                pk, signing_message(client_id, req_no, payload), sig
            )
        )
    return out


def kernel_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], signatures batched
    onto the accelerator (ops.ed25519.verify_batch); the client-identity
    binding (pk == registry pk) stays host-side."""
    from ..ops.ed25519 import verify_batch

    cache = _PK_CACHE
    out = [False] * len(items)
    pks, msgs, sigs, slots = [], [], [], []
    for slot, (client_id, req_no, data) in enumerate(items):
        parts = split_signed(data)
        if parts is None:
            continue
        payload, sig, pk = parts
        if pk != _expected_pk(client_id, cache):
            continue
        pks.append(pk)
        msgs.append(signing_message(client_id, req_no, payload))
        sigs.append(sig)
        slots.append(slot)
    if slots:
        for slot, valid in zip(slots, verify_batch(pks, msgs, sigs)):
            out[slot] = bool(valid)
    return out


def pallas_verifier(items: list) -> list:
    """items: [(client_id, req_no, data)] -> [bool], via the full Pallas
    pipeline (device point decompression + windowed ladder,
    ops.ed25519_pallas.verify_batch_pallas); the client-identity binding
    (pk == registry pk) stays host-side."""
    from ..ops.ed25519_pallas import verify_batch_pallas

    cache = _PK_CACHE
    out = [False] * len(items)
    pks, msgs, sigs, slots = [], [], [], []
    for slot, (client_id, req_no, data) in enumerate(items):
        parts = split_signed(data)
        if parts is None:
            continue
        payload, sig, pk = parts
        if pk != _expected_pk(client_id, cache):
            continue
        pks.append(pk)
        msgs.append(signing_message(client_id, req_no, payload))
        sigs.append(sig)
        slots.append(slot)
    if slots:
        for slot, valid in zip(slots, verify_batch_pallas(pks, msgs, sigs)):
            out[slot] = bool(valid)
    return out


class SignaturePlane:
    """Deferred, coalesced request authentication.

    Requests are submitted at schedule time (the client broadcast) and
    judged at first delivery — at which point everything pending verifies
    as one batch.  Verdicts are cached by (client_id, req_no, data), so
    each distinct request is verified exactly once no matter how many
    replicas receive it.  Deterministic: verdicts depend only on the data.
    """

    def __init__(self, verifier=host_verifier):
        self.verifier = verifier
        self._pending: list = []  # [(client_id, req_no, data)]
        self._verdicts: dict = {}
        self.flush_sizes: list[int] = []
        # Blocking wall time per flush — the ingress-auth latency the
        # replica actually experiences (the bench's rung-3 verify p99).
        self.flush_wall_s: list[float] = []

    def _key(self, client_id: int, req_no: int, data: bytes):
        return (client_id, req_no, data)

    def submit(self, client_id: int, req_no: int, data: bytes) -> None:
        key = self._key(client_id, req_no, data)
        if key not in self._verdicts:
            self._pending.append((client_id, req_no, data))
            self._verdicts[key] = None  # reserved: pending


    def valid(self, client_id: int, req_no: int, data: bytes) -> bool:
        key = self._key(client_id, req_no, data)
        verdict = self._verdicts.get(key)
        if verdict is None:
            self.submit(client_id, req_no, data)  # no-op if already pending
            self._flush()
            verdict = self._verdicts[key]
        return verdict

    def _flush(self) -> None:
        if not self._pending:
            return
        import time

        batch = self._pending
        self._pending = []
        self.flush_sizes.append(len(batch))
        start = time.perf_counter()
        verdicts = self.verifier(batch)
        self.flush_wall_s.append(time.perf_counter() - start)
        for item, verdict in zip(batch, verdicts, strict=True):
            self._verdicts[self._key(*item)] = verdict
