"""The cluster supervisor: spawn, wire, probe, kill, restart, tear down.

``ClusterSupervisor`` runs an N-node mirbft-tpu cluster as N real OS
processes (``python -m mirbft_tpu.cluster`` workers) under one scratch
root, one directory per node (spec.json, address.json, peers.json,
worker.log, wal/, reqs/, app.log, checkpoints.jsonl, metrics.json).

Lifecycle is a filesystem + HTTP handshake (the worker side is
documented in worker.py):

- ``start()`` writes each node's spec, spawns the workers with stdout
  and stderr redirected to the node's ``worker.log``, collects every
  ``address.json``, optionally interposes a ``PartitionProxy`` on each
  directed edge, publishes ``peers.json``, and polls ``/healthz`` until
  every node reports ``ready: true``.
- ``kill(node, graceful=False)`` is SIGKILL — the real crash the
  in-process chaos driver can only approximate; ``graceful=True`` is
  SIGTERM + drain.  ``restart(node)`` respawns from the node's on-disk
  WAL/reqstore on the *same* transport port, so peer address books and
  proxy upstreams survive the reboot.
- ``teardown()`` SIGTERMs everything, escalates to SIGKILL after a
  grace period, closes proxies, and removes the scratch root.

Client traffic enters through ``submit()``: a dedicated client-side
``TcpTransport`` dials every node directly (client frames bypass the
partition proxies — a partitioned node is cut off from its *peers*, not
from its clients) and ships bare ``pb.Request`` frames that the worker's
transport hands to ``Node.propose``.

``poll_commits()`` tails every node's fsynced ``app.log`` incrementally
and returns newly observed commits ``(node, client_id, req_no, seq,
ts_ns)`` — the ground truth the load generator and the mp chaos driver
both audit.

This module is the reason lint rule W11 exists: ``subprocess`` (and
``multiprocessing``) are confined to ``mirbft_tpu/cluster/`` so no other
package grows an accidental dependency on process spawning.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from .. import pb
from ..chaos.live import PartitionProxy
from ..runtime.transport import TcpTransport
from .profiles import WAN_PROFILES, profile_latency
from .worker import read_json, write_json_atomic

# The client-side transport's endpoint id: far outside any node id range
# (workers discard it — propose frames carry no peer identity).
_CLIENT_NODE_ID = 1 << 20


class WorkerDied(RuntimeError):
    """A worker process exited while the supervisor still needed it."""


class _NodeHandle:
    """Supervisor-side state for one worker process."""

    def __init__(self, node_id: int, directory: str):
        self.node_id = node_id
        self.dir = directory
        self.spec_path = os.path.join(directory, "spec.json")
        self.process: subprocess.Popen | None = None
        self.log_file = None
        self.transport_port = 0
        self.metrics_port = 0
        self.app_port = 0  # KV service port (app mode only)
        # app.log tail state (poll_commits)
        self.log_offset = 0
        self.log_remainder = b""
        self.commits: list = []  # [(client_id, req_no, seq)]
        self.chain = ""

    @property
    def alive(self) -> bool:
        return self.process is not None and self.process.poll() is None

    def log_tail(self, max_bytes: int = 4096) -> str:
        try:
            with open(os.path.join(self.dir, "worker.log"), "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - max_bytes))
                return fh.read().decode("utf-8", "replace")
        except OSError:
            return "<no worker.log>"


class ClusterSupervisor:
    """Boot and manage a multi-process mirbft-tpu cluster."""

    def __init__(
        self,
        node_count: int = 4,
        client_ids=None,
        *,
        root: str | None = None,
        batch_size: int = 1,
        processor: str = "serial",
        profile: str = "lan",
        latency: dict | None = None,
        latency_seed: int = 0,
        tick_seconds: float = 0.04,
        proxied: bool = False,
        keep_root: bool = False,
        deferred_nodes=(),
        checkpoint_interval: int | None = None,
        network_config: dict | None = None,
        app: str | None = None,
        trace: bool = False,
        link_auth: bool = False,
        auth_secret: str = "",
        signed_ingress: bool = False,
    ):
        if profile not in WAN_PROFILES:
            raise ValueError(
                f"unknown WAN profile {profile!r}; choose from "
                f"{sorted(WAN_PROFILES)}"
            )
        self.node_count = node_count
        self.client_ids = list(client_ids) if client_ids else [1, 2]
        self.batch_size = batch_size
        self.processor = processor
        self.profile = profile
        # Explicit per-link map wins over the named profile.
        self.latency = (
            latency
            if latency is not None
            else profile_latency(profile, node_count)
        )
        self.latency_seed = latency_seed
        self.tick_seconds = tick_seconds
        self.proxied = proxied
        self.keep_root = keep_root
        self._own_root = root is None
        self.root = (
            root
            if root is not None
            else tempfile.mkdtemp(prefix="mirbft-cluster-")
        )
        self.nodes = [
            _NodeHandle(n, os.path.join(self.root, f"node{n}"))
            for n in range(node_count)
        ]
        self.proxies: dict = {}  # (src, dst) -> PartitionProxy
        # Reconfiguration under fire: ``deferred_nodes`` are provisioned
        # members of the network config that start() does NOT spawn.
        # Every fresh worker then boots with the running subset as its
        # bootstrap leader set (identical FEntry everywhere), so the
        # absent members own no buckets until join_node() spawns them.
        self.deferred: set = set(int(n) for n in deferred_nodes)
        if self.deferred - set(range(node_count)):
            raise ValueError("deferred_nodes outside the provisioned set")
        if self.deferred:
            quorum = node_count - (node_count - 1) // 3
            if len(self.deferred) > node_count - quorum:
                raise ValueError(
                    "deferring that many nodes leaves no boot quorum"
                )
        self._boot_leaders = (
            sorted(set(range(node_count)) - self.deferred)
            if self.deferred
            else None
        )
        self.checkpoint_interval = checkpoint_interval
        # Explicit genesis NetworkConfig spec dict (nodes/f/buckets/ci/
        # mel) every fresh incumbent boots under.  For dynamic-membership
        # runs this is the *pre-reconfig* subset config; the joiner gets
        # the post-reconfig target via join_node(network_config=...) —
        # membership authority is the committed Reconfiguration, never a
        # static spec.
        self.network_config = dict(network_config) if network_config else None
        self.app = app  # "kv" installs the replicated KV service per node
        # Signed-mode knobs (docs/CRYPTO.md): MAC-authenticated replica
        # channels (all workers share auth_secret) and the speculative
        # Ed25519 ingress stage for client requests.
        if link_auth and not auth_secret:
            raise ValueError("link_auth requires auth_secret")
        self.link_auth = link_auth
        self.auth_secret = auth_secret
        self.signed_ingress = signed_ingress
        # Per-node milestone tracing: each worker dumps <dir>/trace.json
        # (clock_sync-stamped) on graceful shutdown, the input for
        # obsv --critpath / the knee rung's saturation attribution.
        self.trace = trace
        self._booted: set = set()  # ids with a known transport address
        # Guards the client transport handle: submit() runs on load
        # generator threads while teardown() runs on the driver thread,
        # and an unguarded check-then-use would race the close-and-None.
        self._lock = threading.Lock()
        self._client_transport: TcpTransport | None = None  # guarded-by: _lock
        self._started = False

    # -- boot ----------------------------------------------------------------

    def _spec(
        self,
        node_id: int,
        fresh: bool,
        transport_port: int,
        network_config: dict | None = None,
    ) -> dict:
        latency = {
            str(peer): link
            for peer, link in self.latency.items()
            if int(peer) != node_id
        }
        spec = {
            "node_id": node_id,
            "node_count": self.node_count,
            "client_ids": self.client_ids,
            "dir": self.nodes[node_id].dir,
            "root": self.root,
            "batch_size": self.batch_size,
            "processor": self.processor,
            "tick_seconds": self.tick_seconds,
            "transport_port": transport_port,
            "fresh": fresh,
            "latency": latency,
            "latency_seed": self.latency_seed,
        }
        explicit = network_config or self.network_config
        if explicit is not None:
            spec["network_config"] = dict(explicit)
        if self._boot_leaders is not None:
            # Every fresh worker (including a later joiner) builds the
            # same bootstrap FEntry, so the deterministic initial state
            # matches across the whole provisioned member set.
            spec["initial_leaders"] = self._boot_leaders
        if self.checkpoint_interval is not None:
            spec["checkpoint_interval"] = int(self.checkpoint_interval)
        if self.app is not None:
            spec["app"] = self.app
        if self.trace:
            spec["trace"] = True
        if self.link_auth:
            spec["link_auth"] = True
            spec["auth_secret"] = self.auth_secret
        if self.signed_ingress:
            spec["signed_ingress"] = True
        return spec

    def _spawn(self, handle: _NodeHandle) -> None:
        # A stale address.json would satisfy the boot wait instantly;
        # the handshake must observe *this* incarnation's ports.
        try:
            os.remove(os.path.join(handle.dir, "address.json"))
        except OSError:
            pass
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # The workers must import this very package even when it is run
        # from a source tree rather than installed (the worker's cwd is
        # the scratch root, not the repo).
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        existing = env.get("PYTHONPATH")
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + existing if existing else pkg_root
        )
        handle.log_file = open(
            os.path.join(handle.dir, "worker.log"), "ab"
        )
        handle.process = subprocess.Popen(
            [sys.executable, "-m", "mirbft_tpu.cluster", "--spec", handle.spec_path],
            stdout=handle.log_file,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=self.root,
        )

    def _wait_address(self, handle: _NodeHandle, deadline: float) -> None:
        path = os.path.join(handle.dir, "address.json")
        while True:
            doc = read_json(path)
            if doc is not None:
                handle.transport_port = int(doc["transport_port"])
                handle.metrics_port = int(doc["metrics_port"])
                handle.app_port = int(doc.get("app_port", 0))
                return
            if not handle.alive:
                raise WorkerDied(
                    f"node {handle.node_id} exited during boot "
                    f"(rc={handle.process.returncode}):\n{handle.log_tail()}"
                )
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"node {handle.node_id} never wrote address.json:\n"
                    f"{handle.log_tail()}"
                )
            time.sleep(0.02)

    def healthz(self, node_id: int) -> dict | None:
        """One /healthz probe; None when the endpoint is unreachable."""
        port = self.nodes[node_id].metrics_port
        if not port:
            return None
        url = f"http://127.0.0.1:{port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=1.0) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def _wait_ready(self, handle: _NodeHandle, deadline: float) -> None:
        while True:
            doc = self.healthz(handle.node_id)
            if doc is not None and doc.get("ready"):
                return
            if not handle.alive:
                raise WorkerDied(
                    f"node {handle.node_id} exited before ready "
                    f"(rc={handle.process.returncode}):\n{handle.log_tail()}"
                )
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"node {handle.node_id} never reported ready:\n"
                    f"{handle.log_tail()}"
                )
            time.sleep(0.05)

    def _peer_address(self, src: int, dst: int) -> tuple:
        if self.proxied:
            return self.proxies[(src, dst)].address
        return ("127.0.0.1", self.nodes[dst].transport_port)

    def _publish_peers(self, node_id: int) -> None:
        # Only peers with a known address (deferred members appear once
        # join_node boots them; workers re-poll peers.json and dial the
        # newcomers).
        peers = {
            str(peer): list(self._peer_address(node_id, peer))
            for peer in sorted(self._booted)
            if peer != node_id
        }
        write_json_atomic(
            os.path.join(self.nodes[node_id].dir, "peers.json"),
            {"peers": peers},
        )

    def _boot_handles(self) -> list:
        return [h for h in self.nodes if h.node_id not in self.deferred]

    def start(self, timeout_s: float = 120.0) -> None:
        """Boot the cluster (minus deferred members) and block until
        every spawned node is ready."""
        if self._started:
            raise RuntimeError("cluster already started")
        self._started = True
        deadline = time.monotonic() + timeout_s
        for handle in self._boot_handles():
            os.makedirs(handle.dir, exist_ok=True)
            write_json_atomic(
                handle.spec_path,
                self._spec(handle.node_id, fresh=True, transport_port=0),
            )
            self._spawn(handle)
        for handle in self._boot_handles():
            self._wait_address(handle, deadline)
            self._booted.add(handle.node_id)
        if self.proxied:
            for a in sorted(self._booted):
                for b in sorted(self._booted):
                    if a != b:
                        self.proxies[(a, b)] = PartitionProxy(
                            ("127.0.0.1", self.nodes[b].transport_port)
                        )
        for handle in self._boot_handles():
            self._publish_peers(handle.node_id)
        for handle in self._boot_handles():
            self._wait_ready(handle, deadline)
        client_transport = TcpTransport(
            _CLIENT_NODE_ID,
            port=0,
            backoff_base=0.02,
            backoff_cap=0.25,
            dial_timeout=1.0,
        )
        for handle in self._boot_handles():
            client_transport.connect(
                handle.node_id, ("127.0.0.1", handle.transport_port)
            )
        with self._lock:
            self._client_transport = client_transport

    def join_node(
        self,
        node_id: int,
        timeout_s: float = 60.0,
        network_config: dict | None = None,
    ) -> None:
        """Reconfiguration under fire: spawn a deferred member fresh
        against the running cluster.  The joiner boots the same
        deterministic provisioned state (and bootstrap leader set) as
        everyone else, dials the incumbents, and catches up to the
        commit frontier via snapshot state transfer; the incumbents
        pick its address up from the re-published peers.json on their
        next poll."""
        if node_id not in self.deferred:
            raise ValueError(f"node {node_id} is not a deferred member")
        handle = self.nodes[node_id]
        if handle.alive:
            raise RuntimeError(f"node {node_id} is already running")
        deadline = time.monotonic() + timeout_s
        os.makedirs(handle.dir, exist_ok=True)
        write_json_atomic(
            handle.spec_path,
            self._spec(
                node_id, fresh=True, transport_port=0,
                network_config=network_config,
            ),
        )
        self._spawn(handle)
        self._wait_address(handle, deadline)
        self.deferred.discard(node_id)
        self._booted.add(node_id)
        if self.proxied:
            for peer in sorted(self._booted):
                if peer == node_id:
                    continue
                self.proxies[(node_id, peer)] = PartitionProxy(
                    ("127.0.0.1", self.nodes[peer].transport_port)
                )
                self.proxies[(peer, node_id)] = PartitionProxy(
                    ("127.0.0.1", handle.transport_port)
                )
        for peer in sorted(self._booted):
            self._publish_peers(peer)
        self._wait_ready(handle, deadline)
        with self._lock:
            client_transport = self._client_transport
        if client_transport is not None:
            client_transport.connect(
                node_id, ("127.0.0.1", handle.transport_port)
            )

    # -- lifecycle -----------------------------------------------------------

    def kill(self, node_id: int, graceful: bool = False, timeout_s: float = 15.0) -> None:
        """Stop one node: SIGTERM + drain when graceful, SIGKILL when not
        (the chaos crash path — nothing un-fsynced survives)."""
        handle = self.nodes[node_id]
        if handle.process is None:
            return
        if handle.alive:
            if graceful:
                handle.process.send_signal(signal.SIGTERM)
                try:
                    handle.process.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    handle.process.kill()
                    handle.process.wait(timeout=timeout_s)
            else:
                handle.process.kill()
                handle.process.wait(timeout=timeout_s)
        if handle.log_file is not None:
            handle.log_file.close()
            handle.log_file = None
        handle.process = None
        if not graceful:
            # SIGKILL gave the worker no chance to flush; its continuous
            # autoflush did, so reap the newest committed segment and
            # stamp how the process actually died.
            self._reap_flight(handle, "sigkill-reaped")

    def _reap_flight(self, handle: _NodeHandle, reason: str) -> str | None:
        """Annotate the node's newest flight segment with the real cause
        of death (the worker believed its last flush was routine).
        Returns the segment path, or None when the node never flushed."""
        from ..obsv.recorder import annotate_dump, load_dumps

        dumps = load_dumps(os.path.join(handle.dir, "flight"))
        entry = dumps.get(handle.node_id)
        if entry is None:
            return None
        path, _dump = entry
        annotate_dump(path, reason=reason)
        return path

    def flight_dumps(self) -> dict:
        """Newest flight-recorder segment per node id (postmortem
        input): feed ``self.root`` — or any one path's directory — to
        ``python -m mirbft_tpu.obsv --postmortem``."""
        from ..obsv.recorder import load_dumps

        out = {}
        for handle in self.nodes:
            dumps = load_dumps(os.path.join(handle.dir, "flight"))
            entry = dumps.get(handle.node_id)
            if entry is not None:
                out[handle.node_id] = entry[0]
        return out

    def restart(self, node_id: int, timeout_s: float = 60.0) -> None:
        """Respawn a killed node from its on-disk state, on its original
        transport port."""
        handle = self.nodes[node_id]
        if handle.alive:
            raise RuntimeError(f"node {node_id} is still running")
        write_json_atomic(
            handle.spec_path,
            self._spec(
                node_id, fresh=False, transport_port=handle.transport_port
            ),
        )
        deadline = time.monotonic() + timeout_s
        self._spawn(handle)
        self._wait_address(handle, deadline)
        self._wait_ready(handle, deadline)

    def alive_nodes(self) -> list:
        return [h.node_id for h in self.nodes if h.alive]

    def app_addresses(self) -> dict:
        """KV service endpoints: node_id -> (host, port) for every booted
        node with a service (requires ``app="kv"``).  Re-read after a
        restart — workers re-bind an ephemeral service port."""
        out = {}
        for handle in self.nodes:
            if handle.alive and handle.app_port:
                out[handle.node_id] = ("127.0.0.1", handle.app_port)
        return out

    @property
    def node_ids(self) -> list:
        """The load generator's duck interface (see loadgen.generator)."""
        return [h.node_id for h in self.nodes]

    # -- partitions ----------------------------------------------------------

    def set_partition(self, groups, cut: bool) -> None:
        """Cut (or heal) every proxied edge crossing the group boundary;
        requires ``proxied=True`` at construction."""
        if not self.proxied:
            raise RuntimeError(
                "set_partition requires ClusterSupervisor(proxied=True)"
            )
        group_of = {}
        for gi, group in enumerate(groups):
            for node in group:
                group_of[node] = gi
        for a in range(self.node_count):
            for b in range(self.node_count):
                if a != b and group_of.get(a) != group_of.get(b):
                    proxy = self.proxies.get((a, b))
                    if proxy is not None:  # edge to a not-yet-joined node
                        proxy.set_cut(cut)

    # -- client traffic ------------------------------------------------------

    def submit(self, node_id: int, request: pb.Request) -> None:
        """Ship one client request to one node (fire-and-forget; the
        transport's reconnect backoff absorbs a down target).

        Thread-safe against teardown(): the handle is snapshotted under
        the lock, so a concurrent teardown yields either this clean
        RuntimeError or a harmless propose into a closing transport
        (frames to a closed transport are dropped and counted) — never
        an AttributeError from the check-then-use window."""
        with self._lock:
            client_transport = self._client_transport
        if client_transport is None:
            raise RuntimeError("cluster not started")
        client_transport.propose(node_id, request)

    # -- commit observation --------------------------------------------------

    def poll_commits(self) -> list:
        """Incrementally tail every node's app.log; returns newly seen
        commits as ``(node_id, client_id, req_no, seq, ts_ns)``.  Torn or
        garbled lines (crash tails) are skipped, not fatal."""
        out = []
        for handle in self.nodes:
            path = os.path.join(handle.dir, "app.log")
            try:
                with open(path, "rb") as fh:
                    fh.seek(handle.log_offset)
                    chunk = fh.read()
            except OSError:
                continue
            if not chunk:
                continue
            handle.log_offset += len(chunk)
            data = handle.log_remainder + chunk
            lines = data.split(b"\n")
            handle.log_remainder = lines.pop()
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                handle.chain = rec.get("chain", handle.chain)
                if rec.get("t") != "apply":
                    continue
                ts_ns = rec.get("ts_ns")
                for client_id, req_no, _digest in rec["reqs"]:
                    handle.commits.append((client_id, req_no, rec["seq"]))
                    out.append(
                        (handle.node_id, client_id, req_no, rec["seq"], ts_ns)
                    )
        return out

    def committed(self, node_id: int) -> list:
        """Every commit observed so far on one node (tail first)."""
        self.poll_commits()
        return list(self.nodes[node_id].commits)

    def chains(self) -> list:
        """Last observed app-chain hex digest per node (tail first)."""
        self.poll_commits()
        return [h.chain for h in self.nodes]

    # -- teardown ------------------------------------------------------------

    def teardown(self) -> None:
        """Stop everything; idempotent."""
        with self._lock:
            client_transport = self._client_transport
            self._client_transport = None
        if client_transport is not None:
            client_transport.close(0)
        for handle in self.nodes:
            if handle.alive:
                handle.process.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 15.0
        for handle in self.nodes:
            if handle.process is None:
                continue
            remaining = max(0.1, deadline - time.monotonic())
            try:
                handle.process.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                handle.process.kill()
                handle.process.wait(timeout=10.0)
            if handle.log_file is not None:
                handle.log_file.close()
                handle.log_file = None
            handle.process = None
        for proxy in self.proxies.values():
            proxy.close()
        self.proxies = {}
        if self._own_root and not self.keep_root:
            shutil.rmtree(self.root, ignore_errors=True)

    def __enter__(self) -> "ClusterSupervisor":
        self.start()
        return self

    def __exit__(self, *_exc) -> None:
        self.teardown()
