"""The per-node worker process: one OS process per consensus node.

``python -m mirbft_tpu.cluster --spec <node_dir>/spec.json`` runs one
node end to end: storage under the node directory, a serializer-owned
protocol core (``runtime.Node``), a ``TcpTransport`` mesh link, and the
standard consumer loop driving the selected processor.  The supervisor
(supervisor.py) owns process lifecycle; this module owns everything that
happens inside one process.

Boot is a two-phase handshake over the shared filesystem (every process
runs on one host — the multi-*process* cluster is about real OS-level
isolation, kill -9 fidelity, and true parallelism, not distribution):

1. The worker binds its transport + metrics ports, then atomically
   writes ``address.json`` (tmp + rename) with its pid and bound ports.
   ``/healthz`` reports ``ready: false`` during this window.
2. The supervisor collects every node's ``address.json``, builds the
   (optionally proxied) peer address map, and writes ``peers.json`` into
   each node directory.  The worker polls for that file, dials every
   peer, applies the spec's per-link latency profile, and only then
   flips ``/healthz`` to ``ready: true`` — so one HTTP poll tells the
   supervisor the true mesh is wired.

State transfer runs over the real transport: each worker feeds its
stable checkpoints (app chain + uncommitted-request slice) to a
``runtime.transfer.TransferEngine``, which serves digest-chained
snapshot chunks to behind peers on the transport's reserved transfer
lane and fetches/verifies/installs them when this node is the one
behind (staging the verified blob under the node dir, so SIGKILL
mid-transfer resumes without the network after restart).  Workers still
append every checkpoint they compute to ``checkpoints.jsonl`` — the
supervisor's progress monitor reads it — and periodically publish the
engine's counters to ``transfer.json`` for the chaos audits.
Checkpoint records are soft state — rebuilt from consensus on restart —
so they are flushed but not fsynced (durability fsyncs stay in
storage.py, transfer.py and chaos/live.py, per lint rules W10/W17).

Workers also re-poll ``peers.json`` while running: when the supervisor
grows the mesh (``join_node``), every incumbent picks up the newcomer's
address on the next poll and dials it, so the joiner can receive
checkpoint broadcasts and serve/fetch snapshots without any restart.

On SIGTERM the worker drains the processor, closes storage cleanly, and
dumps a final ``metrics.json`` registry snapshot; SIGKILL (the chaos
crash path) gets none of that, which is exactly the point.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

from .. import pb
from ..app import AppLog, DurableChainLog, KvFrontend, KvService, KvStore
from ..chaos.live import _TransportDuct
from ..obsv import hooks
from ..obsv.metrics import Registry
from ..obsv.recorder import FlightRecorder
from ..obsv.resources import ResourceSampler
from ..runtime import (
    Config,
    FileRequestStore,
    FileWal,
    Node,
    build_processor,
)
from ..runtime.node import NodeStopped, standard_initial_network_state
from ..runtime.reconfig import checkpoint_network_state
from ..runtime.transfer import TransferEngine
from ..runtime.transport import TcpTransport

# How long the worker waits for the supervisor's peers.json before
# concluding it was orphaned.
_PEERS_TIMEOUT_S = 60.0

# Fixed-port rebinds retry through TIME_WAIT for this long (restart path).
_BIND_RETRY_S = 10.0


def write_json_atomic(path: str, payload: dict) -> None:
    """Write ``payload`` via tmp + rename so readers never see a torn
    file — the handshake files (address.json, peers.json) are polled by
    another process."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def read_json(path: str) -> dict | None:
    """Best-effort read of a handshake file; None while absent."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


class Worker:
    """One consensus node inside its own OS process."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.node_id = int(spec["node_id"])
        self.dir = spec["dir"]
        self.root = spec["root"]
        self.tick_seconds = float(spec.get("tick_seconds", 0.04))
        self._stop = threading.Event()
        os.makedirs(self.dir, exist_ok=True)

        registry = Registry()
        # The black box: a bounded ring continuously autoflushed to
        # atomic segments under <dir>/flight/, so even kill -9 (which
        # skips _shutdown entirely) leaves a recent dump for the
        # supervisor to reap and `obsv --postmortem` to merge.
        self.recorder = FlightRecorder(
            self.node_id,
            dump_dir=os.path.join(self.dir, "flight"),
            capacity=int(spec.get("flight_capacity", 512)),
            autoflush_every=int(spec.get("flight_autoflush", 256)),
            registry=registry,
        )
        # Spec "trace": capture milestone instants/flows in-process and
        # dump <dir>/trace.json on graceful shutdown (clock_sync-stamped
        # in wire(), so obsv --critpath / --merge can align the nodes).
        self._trace = bool(spec.get("trace", False))
        _, self.tracer = hooks.enable(
            registry=registry, trace=self._trace, recorder=self.recorder
        )
        self.wal = FileWal(os.path.join(self.dir, "wal"))
        self.reqstore = FileRequestStore(os.path.join(self.dir, "reqs"))
        # The KV app (spec "app": "kv") layers the commit stream + state
        # machine over the durable journal; journal payload mode makes
        # the journal the restart replay source for the state machine.
        self.app_kind = spec.get("app")
        self._journal = DurableChainLog(
            os.path.join(self.dir, "app.log"),
            self.node_id,
            timestamps=True,
            data_source=(
                self.reqstore.get if self.app_kind == "kv" else None
            ),
        )
        self.sampler = ResourceSampler(
            registry=registry,
            recorder=self.recorder,
            interval_s=float(spec.get("resource_interval_s", 1.0)),
            dirs={
                "wal": os.path.join(self.dir, "wal"),
                "reqstore": os.path.join(self.dir, "reqs"),
            },
            node=self.node_id,
        ).start()
        config = Config(
            id=self.node_id,
            batch_size=int(spec.get("batch_size", 1)),
            processor=spec.get("processor", "serial"),
            metrics_port=0,
            link_auth=bool(spec.get("link_auth", False)),
            auth_secret=str(spec.get("auth_secret", "")).encode(),
        )
        self.config = config
        if spec.get("fresh", True):
            # Scenario override (join/catch-up tests shrink the window so
            # a joiner falls a full certified checkpoint behind quickly);
            # identical in every spec, so fresh boots stay deterministic.
            ci = spec.get("checkpoint_interval")
            explicit = spec.get("network_config")
            if explicit:
                # A reconfiguration boot: the genesis config is dictated
                # verbatim (for a joiner, the exact target config the
                # committed Reconfiguration carries; for incumbents, the
                # pre-reconfig member subset) — membership authority is
                # the committed op, not the process roster.
                state = pb.NetworkState(
                    config=pb.NetworkConfig(
                        nodes=[int(n) for n in explicit["nodes"]],
                        f=int(explicit["f"]),
                        number_of_buckets=int(explicit["number_of_buckets"]),
                        checkpoint_interval=int(
                            explicit["checkpoint_interval"]
                        ),
                        max_epoch_length=int(explicit["max_epoch_length"]),
                    ),
                    clients=[
                        pb.NetworkClient(id=int(cid), width=100)
                        for cid in spec["client_ids"]
                    ],
                )
            else:
                state = standard_initial_network_state(
                    int(spec["node_count"]),
                    list(spec["client_ids"]),
                    checkpoint_interval=int(ci) if ci else None,
                )
            # A provisioned-but-not-yet-running member set (join-under-
            # fire): boot every worker with the running subset as the
            # bootstrap leaders, so absent members own no buckets until
            # they actually join.
            leaders = spec.get("initial_leaders")
            self.node = Node.start_new(
                config,
                state,
                initial_leaders=(
                    [int(n) for n in leaders] if leaders else None
                ),
            )
        else:
            self.node = Node.restart(config, self.wal, self.reqstore)
        self.app_stream = None
        self.kv_service = None
        if self.app_kind == "kv":
            self.kv_store = KvStore()
            self.app_stream = self.node.attach_app(
                self.kv_store,
                state_path=os.path.join(self.dir, "app.state"),
                queue_depth=int(spec.get("app_queue_depth", 256)),
                data_source=self.reqstore.get,
            )
            # Composition replays journaled ops above the persisted
            # snapshot floor into the state machine.
            self.app_log = AppLog(self._journal, self.app_stream)
            self.kv_service = KvService(
                KvFrontend(self.app_stream, self.kv_store, self.node.propose)
            )
        else:
            self.app_log = self._journal
        # Not ready until the peer mesh is dialed (phase 2 below).
        self.node.set_ready(False)
        self.transport = self._bind(int(spec.get("transport_port", 0)))
        self.engine = TransferEngine(
            self.node_id,
            _TransportDuct(self.transport),
            staging_dir=self.dir,
            peers=[
                p
                for p in range(int(spec["node_count"]))
                if p != self.node_id
            ],
            limits=config,
            install=self._install_snapshot,
            complete=self.node.state_transfer_complete,
            failed=self.node.state_transfer_failed,
            chunk_timeout_s=float(spec.get("transfer_chunk_timeout_s", 1.0)),
        )
        self.transport.set_transfer_sink(self.engine.on_frame)
        # Spec "signed_ingress": client requests carry Ed25519 trailers
        # (loadgen ClientModel signed=True) and are speculatively
        # admitted through the batched verify stage — survivors reach
        # node.propose, forgeries are evicted (docs/CRYPTO.md).
        self.ingress = None
        if bool(spec.get("signed_ingress", False)):
            from ..runtime.ingress import SpeculativeIngress
            from ..testengine import signing

            self.ingress = SpeculativeIngress(
                self.node.propose,
                signing.batch_verifier(),
                name=f"ingress-{self.node_id}",
            )
            self.transport.set_propose_sink(self.ingress.submit)
        self._checkpoint_file = open(
            os.path.join(self.dir, "checkpoints.jsonl"), "a", encoding="utf-8"
        )
        self._announced: set = set()
        self._dialed: set = set()

    # -- boot handshake ------------------------------------------------------

    def _bind(self, port: int) -> TcpTransport:
        """Bind the transport; restarts re-bind the recorded port
        (retrying through TIME_WAIT) so peers' registered addresses and
        the supervisor's proxies stay valid across the reboot."""
        deadline = time.monotonic() + _BIND_RETRY_S
        while True:
            try:
                link_auth = None
                if self.config.link_auth:
                    from ..crypto.mac import LinkAuthenticator

                    link_auth = LinkAuthenticator(
                        self.node_id, self.config.auth_secret
                    )
                return TcpTransport(
                    self.node_id,
                    port=port,
                    backoff_base=0.02,
                    backoff_cap=0.25,
                    dial_timeout=1.0,
                    link_auth=link_auth,
                )
            except OSError:
                if port == 0 or time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)

    def announce(self) -> None:
        doc = {
            "pid": os.getpid(),
            "transport_port": self.transport.address[1],
            "metrics_port": self.node.metrics_address[1],
        }
        if self.kv_service is not None:
            doc["app_port"] = self.kv_service.port
        write_json_atomic(os.path.join(self.dir, "address.json"), doc)

    def wire(self) -> None:
        """Phase 2: wait for peers.json, dial the mesh, apply the link
        latency profile, go ready."""
        peers_path = os.path.join(self.dir, "peers.json")
        deadline = time.monotonic() + _PEERS_TIMEOUT_S
        while True:
            peers_doc = read_json(peers_path)
            if peers_doc is not None:
                break
            if self._stop.is_set():
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"node {self.node_id}: no peers.json after "
                    f"{_PEERS_TIMEOUT_S:.0f}s (supervisor gone?)"
                )
            time.sleep(0.02)
        self.transport.serve(self.node)
        self._dial_peers(peers_doc)
        self.processor = build_processor(
            self.node,
            self.transport.link(),
            self.app_log,
            self.wal,
            self.reqstore,
        )
        if hasattr(self.processor, "on_results"):
            self.processor.on_results = self._capture_checkpoints
        # The transport's hello handshake measured peer clock offsets;
        # stamp them into the recorder so --postmortem aligns this
        # node's dump with its peers' exactly like live trace merging.
        self.recorder.set_clock_offsets(self.transport.clock_offsets())
        if self.tracer is not None:
            self.tracer.set_clock_sync(
                self.node_id, self.transport.clock_offsets()
            )
            self.tracer.name_thread(self.node_id, f"node {self.node_id}")
        self.recorder.record_note("worker.ready", args={"pid": os.getpid()})
        # Commit a baseline segment now: a SIGKILL that lands before the
        # first autoflush threshold must still find a dump to annotate.
        self.recorder.flush("ready")
        self.node.set_ready(True)

    def _dial_peers(self, peers_doc: dict) -> None:
        """Dial every peer in a peers.json document that is not yet
        connected.  Idempotent, so the run loop's periodic re-poll only
        adds newcomers (supervisor ``join_node``) — and the transfer
        engine's donor list grows with the mesh."""
        latency = self.spec.get("latency", {})
        seed = int(self.spec.get("latency_seed", 0))
        added = False
        for peer_str, address in peers_doc.get("peers", {}).items():
            peer_id = int(peer_str)
            if peer_id == self.node_id or peer_id in self._dialed:
                continue
            link = latency.get(peer_str) or latency.get(str(peer_id))
            if link:
                # Before connect(): the per-peer channel picks its
                # LinkLatency up at creation, so no frame ever bypasses
                # the emulated delay.
                self.transport.set_link_latency(
                    peer_id,
                    float(link.get("delay_ms", 0.0)) / 1000.0,
                    jitter_s=float(link.get("jitter_ms", 0.0)) / 1000.0,
                    seed=seed,
                )
            self.transport.connect(peer_id, tuple(address))
            self._dialed.add(peer_id)
            added = True
        if added:
            self.engine.set_peers(sorted(self._dialed))

    # -- checkpoints / state transfer ---------------------------------------

    def _capture_checkpoints(self, results) -> None:
        for cr in results.checkpoints:
            seq_no = cr.checkpoint.seq_no
            if seq_no in self._announced:
                continue
            self._announced.add(seq_no)
            state = checkpoint_network_state(cr)
            self._checkpoint_file.write(
                json.dumps(
                    {
                        "seq": seq_no,
                        "value": cr.value.hex(),
                        "state": pb.encode(state).hex(),
                    }
                )
                + "\n"
            )
            self._checkpoint_file.flush()
            requests: list = []

            def _collect(ack, _data=None):
                # FileRequestStore.uncommitted hands only the ack; the
                # payload is a separate read.
                data = self.reqstore.get(ack)
                if data is not None:
                    requests.append((ack, data))

            self.reqstore.uncommitted(_collect)
            if self.app_stream is not None:
                # The certified value binds the full app-state blob; ship
                # the blob so an installer can verify + adopt the whole
                # state machine, not just the chain.
                app_bytes = (
                    self.app_stream.snapshot_blob(cr.value)
                    or self.app_stream.last_snapshot_blob
                    or b""
                )
            else:
                app_bytes = self.app_log.chain
            self.engine.note_checkpoint(
                seq_no, cr.value, state, app_bytes, requests
            )

    def _install_snapshot(self, snap):
        """TransferEngine install callback: adopt the app state (an
        fsynced adopt record; in KV mode the verified full state blob)
        and the donor's uncommitted-request slice, then let the node
        persist the checkpoint CEntry."""
        if self.app_stream is not None:
            if not self.app_log.install(
                snap.app_bytes, snap.value, snap.seq_no
            ):
                return None  # blob does not bind to the certified value
        else:
            self.app_log.adopt(snap.value, snap.seq_no)
        for ack, data in snap.requests:
            self.reqstore.store(ack, data)
        self.reqstore.sync()
        return snap.network_state

    def _publish_transfer_status(self) -> None:
        """Expose the engine's phase and evidence counters for the
        supervisor's chaos audits (corruption-rejection, catch-up)."""
        try:
            write_json_atomic(
                os.path.join(self.dir, "transfer.json"), self.engine.status()
            )
            write_json_atomic(
                os.path.join(self.dir, "reconfig.json"),
                self.node.reconfig_status(),
            )
            if self.app_stream is not None:
                write_json_atomic(
                    os.path.join(self.dir, "app.json"),
                    self.app_stream.status(),
                )
        except OSError:
            pass  # monitoring is best-effort; never kill the consumer

    # -- the consumer loop ---------------------------------------------------

    def run(self) -> int:
        """Drive the node until SIGTERM (or serializer death); returns
        the process exit code."""
        last_tick = time.monotonic()
        last_poll = last_tick
        code = 0
        try:
            while not self._stop.is_set():
                actions = self.node.ready(timeout=0.01)
                if actions is not None:
                    results = self.processor.process(actions)
                    self._capture_checkpoints(results)
                    if results.digests or results.checkpoints:
                        self.node.add_results(results)
                now = time.monotonic()
                if now - last_tick >= self.tick_seconds:
                    last_tick = now
                    self.node.tick()
                if actions is not None and actions.state_transfer is not None:
                    self.engine.begin(actions.state_transfer)
                self.engine.poll()
                if now - last_poll >= 0.5:
                    last_poll = now
                    peers_doc = read_json(
                        os.path.join(self.dir, "peers.json")
                    )
                    if peers_doc is not None:
                        self._dial_peers(peers_doc)
                    self._publish_transfer_status()
                if self.node.retired and actions is None:
                    # An adopted reconfiguration removed this node and the
                    # action queue has drained: exit gracefully.  The
                    # survivors already drop our messages at ingress, so
                    # lingering only wastes their inbound filters.
                    self.recorder.record_note("worker.retired")
                    break
        except NodeStopped:
            pass
        except Exception as err:  # noqa: BLE001 — report, then die nonzero
            print(f"node {self.node_id} consumer died: {err!r}", file=sys.stderr)
            code = 3
        if self.node.exit_error is not None:
            print(
                f"node {self.node_id} serializer died: "
                f"{self.node.exit_error!r}",
                file=sys.stderr,
            )
            code = 4
        self._shutdown(graceful=code == 0)
        return code

    def stop(self) -> None:
        self._stop.set()

    def _shutdown(self, graceful: bool) -> None:
        self.sampler.stop()
        if self.kv_service is not None:
            self.kv_service.close()
        try:
            self.recorder.record_note(
                "worker.shutdown", args={"graceful": graceful}
            )
            self.recorder.flush("exit" if graceful else "sigterm")
        except OSError:
            pass  # a full disk must not block the rest of teardown
        self._publish_transfer_status()
        closer = getattr(self.processor, "close", None)
        if closer is not None:
            try:
                closer()  # drain in-flight batches before storage closes
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
        if self.ingress is not None:
            self.ingress.close(drain_timeout=0.5)
        self.transport.close(0.5)
        self.node.stop()
        self._checkpoint_file.close()
        if graceful:
            self.wal.close()
            self.reqstore.close()
            self.app_log.close()
            snapshot = hooks.metrics.snapshot() if hooks.enabled else {}
            write_json_atomic(
                os.path.join(self.dir, "metrics.json"), snapshot
            )
            if self.tracer is not None:
                try:
                    self.tracer.write(os.path.join(self.dir, "trace.json"))
                except OSError:
                    pass  # trace dump is best-effort, like the recorder
        else:
            self.wal.crash()
            self.reqstore.crash()
            self.app_log.crash()
        hooks.disable()


def run_worker(spec_path: str) -> int:
    """Entry point for ``python -m mirbft_tpu.cluster --spec <path>``."""
    with open(spec_path, "r", encoding="utf-8") as fh:
        spec = json.load(fh)
    worker = Worker(spec)

    def _on_term(_signum, _frame):
        worker.stop()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    worker.announce()
    try:
        worker.wire()
    except Exception as err:  # noqa: BLE001 — boot failure must exit nonzero
        print(f"node {worker.node_id} wiring failed: {err!r}", file=sys.stderr)
        worker._shutdown(graceful=False)
        return 2
    return worker.run()
