"""The per-node worker process: one OS process per consensus node.

``python -m mirbft_tpu.cluster --spec <node_dir>/spec.json`` runs one
node end to end: storage under the node directory, a serializer-owned
protocol core (``runtime.Node``), a ``TcpTransport`` mesh link, and the
standard consumer loop driving the selected processor.  The supervisor
(supervisor.py) owns process lifecycle; this module owns everything that
happens inside one process.

Boot is a two-phase handshake over the shared filesystem (every process
runs on one host — the multi-*process* cluster is about real OS-level
isolation, kill -9 fidelity, and true parallelism, not distribution):

1. The worker binds its transport + metrics ports, then atomically
   writes ``address.json`` (tmp + rename) with its pid and bound ports.
   ``/healthz`` reports ``ready: false`` during this window.
2. The supervisor collects every node's ``address.json``, builds the
   (optionally proxied) peer address map, and writes ``peers.json`` into
   each node directory.  The worker polls for that file, dials every
   peer, applies the spec's per-link latency profile, and only then
   flips ``/healthz`` to ``ready: true`` — so one HTTP poll tells the
   supervisor the true mesh is wired.

State transfer is filesystem-mediated: each worker appends every
checkpoint it computes to ``checkpoints.jsonl`` in its node directory,
and a worker that falls behind scans its peers' checkpoint files for the
target (the cross-process analogue of ``LiveReplica._serve_transfer``).
Checkpoint records are soft state — rebuilt from consensus on restart —
so they are flushed but not fsynced (durability fsyncs stay in
storage.py and chaos/live.py, per lint rule W10).

On SIGTERM the worker drains the processor, closes storage cleanly, and
dumps a final ``metrics.json`` registry snapshot; SIGKILL (the chaos
crash path) gets none of that, which is exactly the point.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

from .. import pb
from ..chaos.live import DurableChainLog
from ..obsv import hooks
from ..obsv.metrics import Registry
from ..obsv.recorder import FlightRecorder
from ..obsv.resources import ResourceSampler
from ..runtime import (
    Config,
    FileRequestStore,
    FileWal,
    Node,
    build_processor,
)
from ..runtime.node import NodeStopped, standard_initial_network_state
from ..runtime.transport import TcpTransport

# How long the worker waits for the supervisor's peers.json before
# concluding it was orphaned.
_PEERS_TIMEOUT_S = 60.0

# Fixed-port rebinds retry through TIME_WAIT for this long (restart path).
_BIND_RETRY_S = 10.0


def write_json_atomic(path: str, payload: dict) -> None:
    """Write ``payload`` via tmp + rename so readers never see a torn
    file — the handshake files (address.json, peers.json) are polled by
    another process."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def read_json(path: str) -> dict | None:
    """Best-effort read of a handshake file; None while absent."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


class Worker:
    """One consensus node inside its own OS process."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.node_id = int(spec["node_id"])
        self.dir = spec["dir"]
        self.root = spec["root"]
        self.tick_seconds = float(spec.get("tick_seconds", 0.04))
        self._stop = threading.Event()
        os.makedirs(self.dir, exist_ok=True)

        registry = Registry()
        # The black box: a bounded ring continuously autoflushed to
        # atomic segments under <dir>/flight/, so even kill -9 (which
        # skips _shutdown entirely) leaves a recent dump for the
        # supervisor to reap and `obsv --postmortem` to merge.
        self.recorder = FlightRecorder(
            self.node_id,
            dump_dir=os.path.join(self.dir, "flight"),
            capacity=int(spec.get("flight_capacity", 512)),
            autoflush_every=int(spec.get("flight_autoflush", 256)),
            registry=registry,
        )
        hooks.enable(registry=registry, trace=False, recorder=self.recorder)
        self.app_log = DurableChainLog(
            os.path.join(self.dir, "app.log"), self.node_id, timestamps=True
        )
        self.wal = FileWal(os.path.join(self.dir, "wal"))
        self.reqstore = FileRequestStore(os.path.join(self.dir, "reqs"))
        self.sampler = ResourceSampler(
            registry=registry,
            recorder=self.recorder,
            interval_s=float(spec.get("resource_interval_s", 1.0)),
            dirs={
                "wal": os.path.join(self.dir, "wal"),
                "reqstore": os.path.join(self.dir, "reqs"),
            },
            node=self.node_id,
        ).start()
        config = Config(
            id=self.node_id,
            batch_size=int(spec.get("batch_size", 1)),
            processor=spec.get("processor", "serial"),
            metrics_port=0,
        )
        if spec.get("fresh", True):
            state = standard_initial_network_state(
                int(spec["node_count"]), list(spec["client_ids"])
            )
            self.node = Node.start_new(config, state)
        else:
            self.node = Node.restart(config, self.wal, self.reqstore)
        # Not ready until the peer mesh is dialed (phase 2 below).
        self.node.set_ready(False)
        self.transport = self._bind(int(spec.get("transport_port", 0)))
        self._checkpoint_file = open(
            os.path.join(self.dir, "checkpoints.jsonl"), "a", encoding="utf-8"
        )
        self._announced: set = set()

    # -- boot handshake ------------------------------------------------------

    def _bind(self, port: int) -> TcpTransport:
        """Bind the transport; restarts re-bind the recorded port
        (retrying through TIME_WAIT) so peers' registered addresses and
        the supervisor's proxies stay valid across the reboot."""
        deadline = time.monotonic() + _BIND_RETRY_S
        while True:
            try:
                return TcpTransport(
                    self.node_id,
                    port=port,
                    backoff_base=0.02,
                    backoff_cap=0.25,
                    dial_timeout=1.0,
                )
            except OSError:
                if port == 0 or time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)

    def announce(self) -> None:
        write_json_atomic(
            os.path.join(self.dir, "address.json"),
            {
                "pid": os.getpid(),
                "transport_port": self.transport.address[1],
                "metrics_port": self.node.metrics_address[1],
            },
        )

    def wire(self) -> None:
        """Phase 2: wait for peers.json, dial the mesh, apply the link
        latency profile, go ready."""
        peers_path = os.path.join(self.dir, "peers.json")
        deadline = time.monotonic() + _PEERS_TIMEOUT_S
        while True:
            peers_doc = read_json(peers_path)
            if peers_doc is not None:
                break
            if self._stop.is_set():
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"node {self.node_id}: no peers.json after "
                    f"{_PEERS_TIMEOUT_S:.0f}s (supervisor gone?)"
                )
            time.sleep(0.02)
        self.transport.serve(self.node)
        latency = self.spec.get("latency", {})
        seed = int(self.spec.get("latency_seed", 0))
        for peer_str, address in peers_doc["peers"].items():
            peer_id = int(peer_str)
            link = latency.get(peer_str) or latency.get(str(peer_id))
            if link:
                # Before connect(): the per-peer channel picks its
                # LinkLatency up at creation, so no frame ever bypasses
                # the emulated delay.
                self.transport.set_link_latency(
                    peer_id,
                    float(link.get("delay_ms", 0.0)) / 1000.0,
                    jitter_s=float(link.get("jitter_ms", 0.0)) / 1000.0,
                    seed=seed,
                )
            self.transport.connect(peer_id, tuple(address))
        self.processor = build_processor(
            self.node,
            self.transport.link(),
            self.app_log,
            self.wal,
            self.reqstore,
        )
        if hasattr(self.processor, "on_results"):
            self.processor.on_results = self._capture_checkpoints
        # The transport's hello handshake measured peer clock offsets;
        # stamp them into the recorder so --postmortem aligns this
        # node's dump with its peers' exactly like live trace merging.
        self.recorder.set_clock_offsets(self.transport.clock_offsets())
        self.recorder.record_note("worker.ready", args={"pid": os.getpid()})
        # Commit a baseline segment now: a SIGKILL that lands before the
        # first autoflush threshold must still find a dump to annotate.
        self.recorder.flush("ready")
        self.node.set_ready(True)

    # -- checkpoints / state transfer ---------------------------------------

    def _capture_checkpoints(self, results) -> None:
        for cr in results.checkpoints:
            seq_no = cr.checkpoint.seq_no
            if seq_no in self._announced:
                continue
            self._announced.add(seq_no)
            state = pb.NetworkState(
                config=cr.checkpoint.network_config,
                clients=cr.checkpoint.clients_state,
                pending_reconfigurations=list(cr.reconfigurations),
            )
            self._checkpoint_file.write(
                json.dumps(
                    {
                        "seq": seq_no,
                        "value": cr.value.hex(),
                        "state": pb.encode(state).hex(),
                    }
                )
                + "\n"
            )
            self._checkpoint_file.flush()

    def _serve_transfer(self, target) -> None:
        """Fill a state-transfer request from a peer's published
        checkpoint file; fail it (the node re-requests later) when no
        peer has announced the target yet."""
        want_value = target.value.hex()
        for peer in range(int(self.spec["node_count"])):
            if peer == self.node_id:
                continue
            path = os.path.join(self.root, f"node{peer}", "checkpoints.jsonl")
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    lines = fh.readlines()
            except OSError:
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a concurrently-written file
                if rec["seq"] == target.seq_no and rec["value"] == want_value:
                    network_state = pb.decode(
                        pb.NetworkState, bytes.fromhex(rec["state"])
                    )
                    self.app_log.adopt(target.value, target.seq_no)
                    self.node.state_transfer_complete(target, network_state)
                    return
        self.node.state_transfer_failed(target)

    # -- the consumer loop ---------------------------------------------------

    def run(self) -> int:
        """Drive the node until SIGTERM (or serializer death); returns
        the process exit code."""
        last_tick = time.monotonic()
        code = 0
        try:
            while not self._stop.is_set():
                actions = self.node.ready(timeout=0.01)
                if actions is not None:
                    results = self.processor.process(actions)
                    self._capture_checkpoints(results)
                    if results.digests or results.checkpoints:
                        self.node.add_results(results)
                now = time.monotonic()
                if now - last_tick >= self.tick_seconds:
                    last_tick = now
                    self.node.tick()
                if actions is not None and actions.state_transfer is not None:
                    self._serve_transfer(actions.state_transfer)
        except NodeStopped:
            pass
        except Exception as err:  # noqa: BLE001 — report, then die nonzero
            print(f"node {self.node_id} consumer died: {err!r}", file=sys.stderr)
            code = 3
        if self.node.exit_error is not None:
            print(
                f"node {self.node_id} serializer died: "
                f"{self.node.exit_error!r}",
                file=sys.stderr,
            )
            code = 4
        self._shutdown(graceful=code == 0)
        return code

    def stop(self) -> None:
        self._stop.set()

    def _shutdown(self, graceful: bool) -> None:
        self.sampler.stop()
        try:
            self.recorder.record_note(
                "worker.shutdown", args={"graceful": graceful}
            )
            self.recorder.flush("exit" if graceful else "sigterm")
        except OSError:
            pass  # a full disk must not block the rest of teardown
        closer = getattr(self.processor, "close", None)
        if closer is not None:
            try:
                closer()  # drain in-flight batches before storage closes
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
        self.transport.close(0.5)
        self.node.stop()
        self._checkpoint_file.close()
        if graceful:
            self.wal.close()
            self.reqstore.close()
            self.app_log.close()
            snapshot = hooks.metrics.snapshot() if hooks.enabled else {}
            write_json_atomic(
                os.path.join(self.dir, "metrics.json"), snapshot
            )
        else:
            self.wal.crash()
            self.reqstore.crash()
            self.app_log.crash()
        hooks.disable()


def run_worker(spec_path: str) -> int:
    """Entry point for ``python -m mirbft_tpu.cluster --spec <path>``."""
    with open(spec_path, "r", encoding="utf-8") as fh:
        spec = json.load(fh)
    worker = Worker(spec)

    def _on_term(_signum, _frame):
        worker.stop()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    worker.announce()
    try:
        worker.wire()
    except Exception as err:  # noqa: BLE001 — boot failure must exit nonzero
        print(f"node {worker.node_id} wiring failed: {err!r}", file=sys.stderr)
        worker._shutdown(graceful=False)
        return 2
    return worker.run()
