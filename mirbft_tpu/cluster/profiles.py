"""Emulated WAN profiles for the multi-process cluster runner.

Each profile is a per-link one-way delay plus uniform jitter, applied at
the transport send queue (``TcpTransport.set_link_latency``) of *every*
directed link, so a profile models a symmetric mesh.  Delays are held in
the sender's per-peer queue — frames stay coalescible and the emulation
adds no extra sockets or threads.

The numbers are deliberately round: ``lan`` is the loopback baseline
(no added delay), ``wan`` approximates a single-continent deployment,
``geo`` a geo-replicated one.  Scenario-specific asymmetric maps can be
passed straight to ``ClusterSupervisor(latency=...)`` instead.
"""

from __future__ import annotations

# profile name -> (one-way delay ms, uniform jitter ms)
WAN_PROFILES: dict = {
    "lan": (0.0, 0.0),
    "wan": (30.0, 5.0),
    "geo": (80.0, 15.0),
}


def profile_latency(profile: str, node_count: int) -> dict:
    """Lower a named profile into the per-link latency map shipped in
    worker specs: ``{peer_id: {"delay_ms": d, "jitter_ms": j}}`` for one
    node (the map is identical for every node in a symmetric profile)."""
    try:
        delay_ms, jitter_ms = WAN_PROFILES[profile]
    except KeyError:
        raise ValueError(
            f"unknown WAN profile {profile!r}; choose from "
            f"{sorted(WAN_PROFILES)}"
        ) from None
    if delay_ms == 0.0 and jitter_ms == 0.0:
        return {}
    return {
        peer: {"delay_ms": delay_ms, "jitter_ms": jitter_ms}
        for peer in range(node_count)
    }
