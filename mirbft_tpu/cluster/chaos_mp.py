"""Chaos scenarios against a real multi-process cluster.

``run_mp_scenario`` is the ``--cluster mp`` lowering of
``chaos.live.run_live_scenario``: the same ``Scenario`` schema, the same
invariant checkers, but each node is a separate OS process under
``ClusterSupervisor``.  Crash points become SIGKILL (true kill -9, not
the in-process approximation), restarts respawn the worker from its
on-disk WAL/reqstore on the same port, and partition windows cut the
supervisor's socket proxies.

Evidence is read from the outside only — the supervisor tails each
node's fsynced app.log — so the audit holds exactly what a crashed
process left on disk, with no in-process shortcuts.

The client load doubles as a retry storm: every request is submitted to
*every* live node, and uncommitted requests are re-submitted on a short
period until convergence.  Request dedup (the client-window watermarks)
must absorb all of it; ``check_no_fork`` fails any scenario in which a
``(client_id, req_no)`` pair commits twice on any node, and the
dedicated ``retry-storm-dedup`` scenario additionally asserts the
exactly-once count while reporting how many duplicate submissions the
cluster absorbed.

Not every live-scenario feature lowers to processes: storage-fault
injection and signed mode need in-process seams, and ``drop_pct``'s
``TransportFault`` lives inside each worker — scenarios using those are
rejected rather than silently weakened.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from types import SimpleNamespace

from .. import pb
from ..app.service import KvClient
from ..chaos.invariants import (
    CrashSnapshot,
    InvariantViolation,
    check_bounded_catchup,
    check_bounded_recovery,
    check_commit_resumption,
    check_config_agreement,
    check_durable_prefix,
    check_linearizable_reads,
    check_no_fork,
)
from ..runtime.reconfig import encode_reconfig_request
from ..chaos.live import MIN_RECOVERY_BOUND_MS, SIM_TICK_MS
from ..chaos.runner import CampaignResult, ScenarioResult
from ..chaos.scenarios import (
    NodeJoin,
    NodeRemoval,
    PartitionWindow,
    Scenario,
    live_smoke_matrix,
)
from .supervisor import ClusterSupervisor
from .worker import read_json

# The mp acceptance pair: a true kill -9 + restart-from-disk, and a
# proxied minority partition with heal — plus the dedup storm.
MP_SMOKE_NAMES = ("crash-restart", "partition-minority")

# The KV-app chaos pair: the same two disruption families with the
# replicated KV state machine installed and live client sessions
# recording an op history that check_linearizable_reads audits.
KV_MP_SMOKE_NAMES = ("kv-crash-restart", "kv-partition-minority")


def kv_mp_matrix() -> list:
    """Crash-restart and partition-minority with the KV app installed:
    ``notes={"app": "kv"}`` makes the driver spawn KV client sessions
    whose recorded read/write history is audited for linearizable
    reads after convergence (docs/APP.md)."""
    base = {s.name: s for s in live_smoke_matrix()}
    out = []
    for name in MP_SMOKE_NAMES:
        src = base[name]
        out.append(
            Scenario(
                name=f"kv-{name}",
                description=f"{src.description} — with the replicated KV "
                "app and linearizable-read audit",
                partitions=src.partitions,
                crashes=src.crashes,
                notes={"app": "kv", "kv_sessions": 2, "kv_ops": 24},
                tags=("kv",) + tuple(src.tags),
            )
        )
    return out


def retry_storm_scenario() -> Scenario:
    """No faults, maximum client hostility: every request submitted to
    every node and re-submitted aggressively until the cluster converges.
    The pass condition is exactly-once commitment everywhere."""
    return Scenario(
        name="retry-storm-dedup",
        description=(
            "duplicate-heavy open retry storm; dedup must absorb every "
            "resubmission"
        ),
        node_count=4,
        client_count=2,
        reqs_per_client=6,
    )


def join_under_partition_scenario() -> Scenario:
    """Reconfiguration under fire, the add-node half: a 5th provisioned
    member is spawned against the running 4-node cluster mid-run, and a
    partition then strands it with only part of the mesh while it is
    still catching up.  The joiner holds no log — the only way it can
    reach the commit frontier is a real snapshot fetch over the
    transport's transfer lane, verified against a 2f+1 checkpoint
    certificate.  The audit demands exactly that: bounded catch-up AND
    ``snapshots_installed >= 1`` in the joiner's published engine
    counters, so live replay can never quietly stand in for transfer."""
    return Scenario(
        name="join-under-partition",
        description=(
            "5th member joins a running cluster mid-traffic, then a "
            "partition strands it with a minority; it must fetch a "
            "certified snapshot over the real transport and reach the "
            "frontier within the catch-up bound"
        ),
        node_count=5,
        client_count=2,
        reqs_per_client=6,
        joins=(NodeJoin(at_ms=4000, node=4, catchup_bound_ms=150_000),),
        # Sim-ms scale to wall: x * tick/500.  The cut lands well after
        # the (blocking, process-spawn) join returns, mid catch-up; the
        # heal leaves the survivors a full traffic tail to converge on.
        partitions=(
            PartitionWindow(
                groups=((0, 1, 4), (2, 3)),
                from_ms=31_250,
                until_ms=68_750,
            ),
        ),
        # Shrink the checkpoint window so the joiner falls a certified
        # checkpoint behind quickly (identical in every worker spec, so
        # fresh boots stay deterministic).
        notes={"checkpoint_interval": 5},
        tags=("mp", "reconfig"),
    )


def remove_under_partition_scenario() -> Scenario:
    """The remove-node half: node 3 is first partitioned away, then
    permanently removed (true kill -9, never restarted) while the
    majority side keeps committing.  The survivors must converge, and
    the corpse's durable log must remain a clean prefix of theirs."""
    return Scenario(
        name="remove-under-partition",
        description=(
            "node 3 is isolated, then permanently removed mid-window; "
            "the 3-node majority keeps committing and the removed "
            "node's durable log stays a clean prefix"
        ),
        node_count=4,
        client_count=2,
        reqs_per_client=6,
        partitions=(
            PartitionWindow(
                groups=((0, 1, 2), (3,)), from_ms=12_500, until_ms=50_000
            ),
        ),
        removes=(NodeRemoval(at_ms=25_000, node=3),),
        tags=("mp", "reconfig"),
    )


def reconfig_add_under_partition_scenario() -> Scenario:
    """Dynamic membership, the add half: the 4 incumbents boot with a
    genesis config that does NOT include node 4.  An admin client then
    submits a ``pb.Reconfiguration`` carrying the grown 5-node config
    through the ordered broadcast; only once an incumbent's published
    reconfig counters show the config *adopted* (stable reconfigured
    checkpoint) does the driver spawn node 4 — booted with the exact
    target config the committed op carried, never a static roster.  A
    2-2 incumbent partition spans the config flip: while it holds no
    quorum exists, so adoption itself must ride out the cut.  The
    joiner still owes the usual evidence: bounded catch-up plus
    ``snapshots_installed >= 1``, and ``check_config_agreement`` audits
    every certified checkpoint config byte-for-byte across nodes."""
    return Scenario(
        name="reconfig-add-under-partition",
        description=(
            "a committed Reconfiguration grows the cluster 4 -> 5 while "
            "a 2-2 incumbent partition spans the config flip; node 4 "
            "joins only after adoption, catches up via certified "
            "snapshot, and no two nodes ever certify divergent configs"
        ),
        node_count=5,
        client_count=2,
        reqs_per_client=6,
        joins=(
            NodeJoin(
                at_ms=2000,
                node=4,
                catchup_bound_ms=150_000,
                via_reconfig=True,
            ),
        ),
        partitions=(
            PartitionWindow(
                groups=((0, 1), (2, 3, 4)),
                from_ms=12_500,
                until_ms=37_500,
            ),
        ),
        notes={"checkpoint_interval": 5},
        recovery_bound_ms=300_000,
        tags=("mp", "reconfig"),
    )


def reconfig_remove_leader_crash_scenario() -> Scenario:
    """Dynamic membership, the remove half: leader 3 is killed (true
    kill -9, never restarted) and the survivors commit a
    ``pb.Reconfiguration`` shrinking the config to exclude it.  The
    3-node quorum must first ride the leader crash (epoch change to
    strip the dead leader's buckets), then adopt the shrunk config at a
    stable checkpoint and keep committing under it — the departure is a
    membership change the protocol agrees on, not just a silent hole in
    the mesh.  The corpse's durable log must stay a clean prefix, and
    ``check_config_agreement`` holds every shared checkpoint config
    byte-identical across survivors and corpse alike."""
    return Scenario(
        name="reconfig-remove-leader-crash",
        description=(
            "leader 3 crashes for good and the survivors commit a "
            "Reconfiguration removing it; commits resume under the "
            "adopted 3-node config within the liveness bound"
        ),
        node_count=4,
        client_count=2,
        reqs_per_client=6,
        removes=(NodeRemoval(at_ms=12_500, node=3, via_reconfig=True),),
        notes={"checkpoint_interval": 5},
        recovery_bound_ms=300_000,
        tags=("mp", "reconfig"),
    )


MP_RECONFIG_NAMES = (
    "join-under-partition",
    "remove-under-partition",
    "reconfig-add-under-partition",
    "reconfig-remove-leader-crash",
)


def mp_reconfig_matrix() -> list:
    """The reconfiguration-under-fire set (mp-only: joining means
    spawning a real OS process against a live mesh): the static-roster
    pair, then the committed-Reconfiguration pair."""
    return [
        join_under_partition_scenario(),
        remove_under_partition_scenario(),
        reconfig_add_under_partition_scenario(),
        reconfig_remove_leader_crash_scenario(),
    ]


def _reconfig_target(scenario: Scenario) -> tuple:
    """The (incumbent, target) config dicts for a via_reconfig scenario.

    The incumbent config is the genesis every booted member starts
    from: the provisioned node set minus deferred joiners.  The target
    is what the committed ``pb.Reconfiguration`` carries: plus the
    joiners, minus the removed.  Bucket count is pinned to the
    incumbent width so the request->bucket mapping survives the flip."""
    nodes = list(range(scenario.node_count))
    joining = {j.node for j in scenario.joins if j.via_reconfig}
    removing = {r.node for r in scenario.removes if r.via_reconfig}
    incumbents = [n for n in nodes if n not in joining]
    target = [n for n in nodes if n not in removing]
    buckets = len(incumbents)
    ci = int(scenario.notes.get("checkpoint_interval") or 5 * buckets)
    mel = 10 * ci

    def cfg(members: list) -> dict:
        return {
            "nodes": list(members),
            "f": (len(members) - 1) // 3,
            "number_of_buckets": buckets,
            "checkpoint_interval": ci,
            "max_epoch_length": mel,
        }

    return cfg(incumbents), cfg(target)


def mp_matrix() -> list:
    """Scenarios run under ``chaos --live --cluster mp``."""
    by_name = {s.name: s for s in live_smoke_matrix()}
    return (
        [by_name[name] for name in MP_SMOKE_NAMES]
        + [retry_storm_scenario()]
        + mp_reconfig_matrix()
    )


def mp_adversary_matrix() -> list:
    """The mp lowering of the adversary campaign: attacks driven at the
    client seam (duplication floods through real submission sockets).
    Wire-level adversaries need the threads cluster's frame-rewriting
    proxies and are rejected here."""
    from ..chaos.scenarios import live_adversary_matrix

    return [
        scenario
        for scenario in live_adversary_matrix()
        if _mp_supported_adversaries(scenario)
        and not scenario.signed
        and scenario.network_state is None
    ]


def _mp_supported_adversaries(scenario: Scenario) -> bool:
    return bool(scenario.adversaries) and all(
        spec.kind == "flood" and spec.msg_kinds == ("Propose",)
        for spec in scenario.adversaries
    )


def _reject_unsupported(scenario: Scenario) -> None:
    unsupported = []
    if scenario.storage_faults:
        unsupported.append("storage_faults")
    if scenario.signed:
        unsupported.append("signed")
    if scenario.drop_pct:
        unsupported.append("drop_pct")
    if scenario.adversaries and not _mp_supported_adversaries(scenario):
        unsupported.append("non-flood adversaries")
    if unsupported:
        raise ValueError(
            f"scenario {scenario.name!r} uses {', '.join(unsupported)}, "
            "which need in-process seams; run it under --cluster threads"
        )


class _MpDriver:
    """One scenario against one multi-process cluster."""

    def __init__(
        self,
        scenario: Scenario,
        tick_seconds: float,
        budget_s: float,
        max_reqs_per_client: int,
        processor: str,
        retry_period_s: float = 0.3,
        seed: int = 0,
    ):
        self.scenario = scenario
        # Propose-flood adversaries lower to multiplied submissions
        # through the real client sockets (seeded, windowed); everything
        # else was rejected by _reject_unsupported.
        self.flood_specs = list(scenario.adversaries)
        self.flooded = 0
        self._rng = random.Random(seed)
        self.tick_seconds = tick_seconds
        self.budget_s = budget_s
        self.reqs_per_client = min(
            scenario.reqs_per_client, max_reqs_per_client
        )
        self.clients = list(range(1, scenario.client_count + 1))
        self.retry_period_s = retry_period_s
        # KV-app mode (notes={"app": "kv"}): live client sessions drive
        # the replicated KV service alongside the raw proposer load and
        # record the op history check_linearizable_reads audits.  KV
        # sessions get consensus client ids above the raw clients'.
        self.app = scenario.notes.get("app")
        self.kv_ops = int(scenario.notes.get("kv_ops", 24))
        kv_sessions = (
            int(scenario.notes.get("kv_sessions", 2))
            if self.app == "kv"
            else 0
        )
        kv_base = max(self.clients, default=0) + 1
        self.kv_client_ids = list(range(kv_base, kv_base + kv_sessions))
        # Dynamic membership (via_reconfig joins/removes): the admin
        # client submits the target config through the ordered
        # broadcast; incumbents boot with a genesis that excludes the
        # joiners, so the only way the member set can change is the
        # committed pb.Reconfiguration.
        self.reconfig_incumbent = None
        self.reconfig_target = None
        self.reconfig_payload = None
        self.admin_client_id = None
        admin_ids: list = []
        if any(j.via_reconfig for j in scenario.joins) or any(
            r.via_reconfig for r in scenario.removes
        ):
            self.reconfig_incumbent, self.reconfig_target = _reconfig_target(
                scenario
            )
            self.reconfig_payload = encode_reconfig_request(
                [
                    pb.Reconfiguration(
                        type=pb.NetworkConfig(
                            nodes=list(self.reconfig_target["nodes"]),
                            f=self.reconfig_target["f"],
                            number_of_buckets=self.reconfig_target[
                                "number_of_buckets"
                            ],
                            checkpoint_interval=self.reconfig_target[
                                "checkpoint_interval"
                            ],
                            max_epoch_length=self.reconfig_target[
                                "max_epoch_length"
                            ],
                        )
                    )
                ]
            )
            self.admin_client_id = (
                max(self.clients + self.kv_client_ids, default=0) + 1
            )
            admin_ids = [self.admin_client_id]
        self.reconfig_submitted = False
        self._last_reconfig_submit = 0.0
        self._adopted_nodes: set = set()  # cached adoption observations
        self.pending_reconfig_joins: dict = {}  # node -> NodeJoin
        self.supervisor = ClusterSupervisor(
            node_count=scenario.node_count,
            client_ids=self.clients + self.kv_client_ids + admin_ids,
            batch_size=scenario.batch_size,
            processor=processor,
            tick_seconds=tick_seconds,
            proxied=bool(scenario.partitions),
            deferred_nodes=tuple(j.node for j in scenario.joins),
            checkpoint_interval=scenario.notes.get("checkpoint_interval"),
            network_config=self.reconfig_incumbent,
            app=self.app,
        )
        self.expected = {
            (client_id, req_no)
            for client_id in self.clients
            for req_no in range(self.reqs_per_client)
        }
        # The dedup scenario must not depend on racing the commit path:
        # every first-pass submission is itself repeated, so duplicates
        # reach the cluster even when it converges before a retry fires.
        self.storm_repeat = 3 if scenario.name == "retry-storm-dedup" else 1
        self._start = None
        self.down: set = set()  # crashed, restart still pending
        self.removed: set = set()  # permanently removed, never restarted
        self.pending_joins: set = {j.node for j in scenario.joins}
        self.join_times_ms: dict = {}  # node -> wall ms the join fired
        self.catchup_times_ms: dict = {}  # node -> first frontier evidence
        self.snapshots: list = []
        self.commit_times_ms: list = []
        self.heal_times_ms: list = []
        self.events_fired = 0
        self.resubmissions = 0
        self._proposer_stop = threading.Event()
        self._proposer = None
        self.kv_history: list = []
        self._kv_stop = threading.Event()
        self._kv_done = threading.Event()
        self._kv_thread = None

    # -- time ----------------------------------------------------------------

    def scale_s(self, sim_ms: int) -> float:
        return sim_ms / SIM_TICK_MS * self.tick_seconds

    def now_ms(self) -> int:
        return int((time.monotonic() - self._start) * 1000)

    # -- client load ---------------------------------------------------------

    def _flood_copies(self) -> int:
        """Extra duplicate submissions the flood adversaries inject for
        one delivery right now (0 when no window is open)."""
        if self._start is None:
            return 0
        now_s = time.monotonic() - self._start
        copies = 0
        for spec in self.flood_specs:
            if now_s < self.scale_s(spec.from_ms):
                continue
            if spec.until_ms is not None and now_s >= self.scale_s(
                spec.until_ms
            ):
                continue
            if (
                spec.rate_pct < 100
                and self._rng.random() * 100.0 >= spec.rate_pct
            ):
                continue
            copies += spec.copies
        return copies

    def _submit(self, client_id: int, req_no: int, first: bool) -> None:
        request = pb.Request(
            client_id=client_id, req_no=req_no, data=b"%d" % req_no
        )
        repeat = self.storm_repeat if first else 1
        for round_no in range(repeat):
            for node_id in self.supervisor.alive_nodes():
                self.supervisor.submit(node_id, request)
                if not first or round_no > 0:
                    self.resubmissions += 1
                copies = self._flood_copies() if self.flood_specs else 0
                for _ in range(copies):
                    self.supervisor.submit(node_id, request)
                self.flooded += copies

    def _propose_all(self, last_event_s: float) -> None:
        ordered = sorted(self.expected)
        # Pace the first pass past the final fault instant so every
        # disruption lands mid-traffic (see LiveCluster._propose_all).
        span_s = max(last_event_s * 1.25, 0.4)
        gap = span_s / max(len(ordered), 1)
        for client_id, req_no in ordered:
            if self._proposer_stop.wait(gap):
                return
            self._submit(client_id, req_no, first=True)
        # The retry storm: keep re-submitting whatever a node has not yet
        # committed; watermark dedup must absorb all of it.
        while not self._proposer_stop.wait(self.retry_period_s):
            committed = set()
            for handle in self.supervisor.nodes:
                committed |= {(c, q) for c, q, _s in handle.commits}
            for client_id, req_no in ordered:
                if (client_id, req_no) not in committed:
                    self._submit(client_id, req_no, first=False)

    # -- KV app sessions -----------------------------------------------------

    def _drive_kv(self) -> None:
        """Drive the KV service with live sessions through the whole run:
        per-session threads alternate puts and committed-mode gets over a
        small shared key space (so read/write intervals overlap — the
        checker's vacuity guard), refreshing service addresses every op
        round so restarted workers' re-bound ports are picked up."""
        addresses: dict = {}
        while not self._kv_stop.is_set():
            addresses = self.supervisor.app_addresses()
            if addresses:
                break
            time.sleep(0.05)
        if not addresses:
            self._kv_done.set()
            return
        homes = sorted(addresses)
        lock = threading.Lock()
        # Lockstep rounds: a disruption can stall one session for whole
        # seconds while its peer races ahead, desyncing the parities
        # below until no read interval overlaps any write.  The barrier
        # keeps every round's read and write concurrent by construction.
        barrier = threading.Barrier(len(self.kv_client_ids))

        def drive(index: int, client_id: int) -> None:
            session = KvClient(
                addresses, client_id, home=homes[index % len(homes)]
            )
            synced = True
            try:
                for op_no in range(self.kv_ops):
                    if synced:
                        try:
                            barrier.wait(timeout=20.0)
                        except threading.BrokenBarrierError:
                            synced = False  # a peer exited; run free
                    if self._kv_stop.is_set():
                        return
                    session.set_addresses(self.supervisor.app_addresses())
                    key = f"k{op_no % 4}"
                    # Opposite parities per session: at each op round one
                    # session writes the key the other is reading.
                    is_read = (op_no + index) % 2 == 1
                    value = b"%d:%d" % (client_id, op_no)
                    t0 = time.monotonic_ns()
                    try:
                        if is_read:
                            resp = session.get(key, timeout=3.0)
                        else:
                            resp = session.put(key, value, timeout=5.0)
                    except OSError:
                        resp = {"status": "error"}
                    t1 = time.monotonic_ns()
                    entry = {
                        "client_id": client_id,
                        "op": "get" if is_read else "put",
                        "key": key,
                        "invoke_ns": t0,
                        "return_ns": t1,
                        "outcome": resp.get("status", "error"),
                        "version": resp.get("version", 0),
                    }
                    if is_read:
                        if resp.get("status") == "ok":
                            entry["value"] = resp.get("value")
                    else:
                        entry["value"] = value.hex()
                    with lock:
                        self.kv_history.append(entry)
            finally:
                barrier.abort()  # never strand a peer at the barrier
                session.close()

        threads = [
            threading.Thread(
                target=drive,
                args=(index, client_id),
                name=f"chaos-kv-{client_id}",
                daemon=True,
            )
            for index, client_id in enumerate(self.kv_client_ids)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        self._kv_done.set()

    # -- fault schedule ------------------------------------------------------

    def schedule(self) -> list:
        events = []
        for window in self.scenario.partitions:
            events.append(
                (self.scale_s(window.from_ms), 0, "cut", window.groups)
            )
            events.append(
                (self.scale_s(window.until_ms), 1, "heal", window.groups)
            )
        for point in self.scenario.crashes:
            events.append((self.scale_s(point.at_ms), 2, "crash", point.node))
            events.append(
                (
                    self.scale_s(point.at_ms + point.restart_delay_ms),
                    3,
                    "restart",
                    point.node,
                )
            )
        for join in self.scenario.joins:
            events.append((self.scale_s(join.at_ms), 4, "join", join.node))
        for removal in self.scenario.removes:
            events.append(
                (self.scale_s(removal.at_ms), 5, "remove", removal.node)
            )
        events.sort(key=lambda e: (e[0], e[1]))
        return events

    def _fire(self, kind: str, payload) -> None:
        if kind == "cut":
            self.supervisor.set_partition(payload, True)
        elif kind == "heal":
            self.supervisor.set_partition(payload, False)
            self.heal_times_ms.append(self.now_ms())
        elif kind == "crash":
            self.supervisor.poll_commits()
            self.snapshots.append(
                CrashSnapshot(
                    node=payload,
                    at_ms=self.now_ms(),
                    committed=list(self.supervisor.nodes[payload].commits),
                )
            )
            self.down.add(payload)
            self.supervisor.kill(payload, graceful=False)
        elif kind == "restart":
            self.supervisor.restart(payload)
            self.down.discard(payload)
            self.heal_times_ms.append(self.now_ms())
        elif kind == "join":
            join = next(
                j for j in self.scenario.joins if j.node == payload
            )
            if join.via_reconfig:
                # Membership authority is the committed op: submit the
                # grown config now, spawn the node only once an
                # incumbent has *adopted* it (_service_reconfig).
                self.pending_reconfig_joins[payload] = join
                self._submit_reconfig()
            else:
                self.supervisor.join_node(payload)
                self.join_times_ms[payload] = self.now_ms()
                # Joining is a disruption end: catch-up traffic starts
                # here.
                self.heal_times_ms.append(self.now_ms())
        elif kind == "remove":
            self.supervisor.poll_commits()
            self.snapshots.append(
                CrashSnapshot(
                    node=payload,
                    at_ms=self.now_ms(),
                    committed=list(self.supervisor.nodes[payload].commits),
                )
            )
            self.removed.add(payload)
            self.supervisor.kill(payload, graceful=False)
            # Removal is permanent; the survivors' recovery clock starts
            # at the removal instant.
            self.heal_times_ms.append(self.now_ms())
            removal = next(
                r for r in self.scenario.removes if r.node == payload
            )
            if removal.via_reconfig:
                # The survivors now agree the departure is a membership
                # change: commit the shrunk config through the normal
                # broadcast path.
                self._submit_reconfig()

    def _observe_catchup(self) -> None:
        """First non-empty app-chain on a joined node = it adopted the
        certified snapshot (or applied its first live batch) — the
        bounded-catchup clock's stop instant."""
        for node, _joined in self.join_times_ms.items():
            if node in self.catchup_times_ms:
                continue
            if self.supervisor.nodes[node].chain:
                self.catchup_times_ms[node] = self.now_ms()

    # -- dynamic membership --------------------------------------------------

    def _submit_reconfig(self) -> None:
        """Fire (or re-fire) the admin client's reconfiguration request
        at every live node.  Resubmission until adoption is deliberate:
        a partition or leader crash can eat the first copy, and the
        client-window dedup absorbs the duplicates."""
        if self.reconfig_payload is None:
            return
        request = pb.Request(
            client_id=self.admin_client_id,
            req_no=0,
            data=self.reconfig_payload,
        )
        for node_id in self.supervisor.alive_nodes():
            self.supervisor.submit(node_id, request)
        self.reconfig_submitted = True
        self._last_reconfig_submit = time.monotonic()

    def _reconfig_counters(self, node: int) -> dict:
        doc = read_json(
            os.path.join(self.supervisor.nodes[node].dir, "reconfig.json")
        )
        return doc if isinstance(doc, dict) else {}

    def _incumbent_nodes(self) -> list:
        """Members booted at cluster start (deferred joiners excluded)
        that are still supposed to be up."""
        return [
            n
            for n in range(self.scenario.node_count)
            if n not in self.pending_joins
            and n not in self.removed
            and n not in self.down
        ]

    def _poll_adoptions(self) -> None:
        for node in self._incumbent_nodes():
            if node in self._adopted_nodes:
                continue
            if int(self._reconfig_counters(node).get("adopted", 0)) >= 1:
                self._adopted_nodes.add(node)

    def _adoption_complete(self) -> bool:
        """Every live incumbent has activated the committed config (the
        convergence gate for via_reconfig scenarios — exiting before
        adoption would make check_config_agreement vacuous)."""
        incumbents = self._incumbent_nodes()
        return bool(incumbents) and all(
            n in self._adopted_nodes for n in incumbents
        )

    def _service_reconfig(self) -> None:
        """Drive the committed-membership-change lifecycle each loop
        turn: resubmit the admin request until some incumbent adopts,
        then spawn pending joiners with the exact target config the
        committed op carried."""
        if not self.reconfig_submitted:
            return
        self._poll_adoptions()
        if not self._adopted_nodes:
            if (
                time.monotonic() - self._last_reconfig_submit
                > self.retry_period_s
            ):
                self._submit_reconfig()
            return
        for node in sorted(self.pending_reconfig_joins):
            self.supervisor.join_node(
                node, network_config=self.reconfig_target
            )
            del self.pending_reconfig_joins[node]
            self.join_times_ms[node] = self.now_ms()
            # Joining is a disruption end: catch-up starts here.
            self.heal_times_ms.append(self.now_ms())

    def _read_checkpoints(self, node: int) -> list:
        """Every (seq_no, pb.NetworkState) the node certified into its
        checkpoints.jsonl, torn tail lines tolerated (the process may
        have been killed mid-write)."""
        path = os.path.join(
            self.supervisor.nodes[node].dir, "checkpoints.jsonl"
        )
        out = []
        try:
            with open(path, encoding="utf-8") as handle:
                for line in handle:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                        state = pb.decode(
                            pb.NetworkState,
                            bytes.fromhex(record["state"]),
                        )
                        out.append((int(record["seq"]), state))
                    except (ValueError, KeyError, TypeError):
                        continue
        except OSError:
            return []
        return out

    def config_evidence(self, timeout_s: float = 15.0) -> tuple:
        """The ``check_config_agreement`` inputs, read from the outside:
        per-node certified checkpoint configs (checkpoints.jsonl), each
        survivor's newest certified config, and the total adoption count
        (reconfig.json).  Waits briefly for every survivor's newest
        checkpoint to carry the target member set — the post-adoption
        checkpoint trails the adoption boundary by one window, and the
        heartbeat keeps sequences trickling, so it lands shortly after
        convergence; a survivor that never gets there surfaces as a
        final-config divergence, which is exactly the violation."""
        target = sorted(self.reconfig_target["nodes"])
        survivors = [
            n
            for n in range(self.scenario.node_count)
            if n not in self.removed
            and n not in self.down
            and (n not in self.pending_joins or n in self.join_times_ms)
        ]
        deadline = time.monotonic() + timeout_s
        final_configs: dict = {}
        while time.monotonic() < deadline:
            final_configs = {}
            for node in survivors:
                entries = self._read_checkpoints(node)
                if not entries:
                    continue
                config = entries[-1][1].config
                if config is not None and sorted(config.nodes) == target:
                    final_configs[node] = pb.encode(config)
            if len(final_configs) == len(survivors):
                break
            time.sleep(0.2)
        # A survivor whose newest certified config never reached the
        # target set goes in as-is: divergence is the finding.
        for node in survivors:
            if node in final_configs:
                continue
            entries = self._read_checkpoints(node)
            if entries and entries[-1][1].config is not None:
                final_configs[node] = pb.encode(entries[-1][1].config)
        checkpoint_configs: dict = {}
        for node in range(self.scenario.node_count):
            if node in self.pending_joins and node not in self.join_times_ms:
                continue  # never booted
            checkpoint_configs[node] = {
                seq: pb.encode(state.config)
                for seq, state in self._read_checkpoints(node)
                if state.config is not None
            }
        adoptions = sum(
            int(self._reconfig_counters(node).get("adopted", 0))
            for node in survivors
        )
        return checkpoint_configs, final_configs, adoptions

    def _reap(self) -> None:
        for handle in self.supervisor.nodes:
            if handle.node_id in self.down or handle.node_id in self.removed:
                continue
            if handle.node_id in self.pending_joins and (
                handle.node_id not in self.join_times_ms
            ):
                continue  # deferred member not spawned yet
            if handle.process is not None and not handle.alive:
                raise InvariantViolation(
                    f"node {handle.node_id} process died without an "
                    f"injected crash (rc={handle.process.returncode}):\n"
                    f"{handle.log_tail()}"
                )

    def _converged(self) -> bool:
        if self.down:
            return False
        full = False
        chains = set()
        for handle in self.supervisor.nodes:
            if handle.node_id in self.removed:
                continue  # permanently gone; survivors carry the audit
            if not handle.alive:
                return False
            pairs = {(c, q) for c, q, _s in handle.commits}
            if self.expected <= pairs:
                full = True
            chains.add(handle.chain)
        return full and len(chains) == 1 and "" not in chains

    # -- the drive loop ------------------------------------------------------

    def run(self) -> int:
        self.supervisor.start()
        self._start = time.monotonic()
        events = self.schedule()
        last_event_s = events[-1][0] if events else 0.0
        self._proposer = threading.Thread(
            target=self._propose_all,
            args=(last_event_s,),
            name="chaos-mp-proposer",
            daemon=True,
        )
        self._proposer.start()
        if self.kv_client_ids:
            self._kv_thread = threading.Thread(
                target=self._drive_kv, name="chaos-mp-kv", daemon=True
            )
            self._kv_thread.start()
        deadline = self._start + self.budget_s
        while time.monotonic() < deadline:
            now_s = time.monotonic() - self._start
            while events and events[0][0] <= now_s:
                _at, _order, kind, payload = events.pop(0)
                self.events_fired += 1
                self._fire(kind, payload)
            if self.supervisor.poll_commits():
                self.commit_times_ms.append(self.now_ms())
            if self.join_times_ms:
                self._observe_catchup()
            if self.reconfig_submitted and (
                self.pending_reconfig_joins or not self._adoption_complete()
            ):
                self._service_reconfig()
            self._reap()
            if (
                not events
                and not self.pending_reconfig_joins
                and (
                    self.reconfig_payload is None
                    or self._adoption_complete()
                )
                and self._converged()
            ):
                return self.now_ms()
            time.sleep(0.02)
        commits = [len(h.commits) for h in self.supervisor.nodes]
        raise InvariantViolation(
            f"no convergence within the {self.budget_s:.0f}s budget "
            f"(per-node commits: {commits}, nodes down: {sorted(self.down)}, "
            f"events unfired: {len(events)})"
        )

    def evidence(self) -> SimpleNamespace:
        self.supervisor.poll_commits()
        return SimpleNamespace(
            node_count=self.scenario.node_count,
            node_states=[
                SimpleNamespace(
                    committed_reqs=list(handle.commits),
                    app_chain=handle.chain,
                    crashed=handle.node_id in self.removed,
                )
                for handle in self.supervisor.nodes
            ],
        )

    def transfer_counters(self, node: int) -> dict:
        """The engine evidence a worker last published to its
        transfer.json (empty when the file never appeared)."""
        doc = read_json(
            os.path.join(self.supervisor.nodes[node].dir, "transfer.json")
        )
        if not doc:
            return {}
        counters = doc.get("counters", {})
        return counters if isinstance(counters, dict) else {}

    def wait_transfer_evidence(self, node: int, timeout_s: float = 3.0) -> dict:
        """Counters once they show an installed/resumed snapshot, or the
        last observation after ``timeout_s``.  Workers publish on a 0.5s
        cadence, so convergence (detected from the fsynced app.log) can
        race a hair ahead of the final counter publish."""
        deadline = time.monotonic() + timeout_s
        counters = self.transfer_counters(node)
        while time.monotonic() < deadline:
            installed = int(counters.get("snapshots_installed", 0)) + int(
                counters.get("snapshots_resumed_staged", 0)
            )
            if installed >= 1:
                break
            time.sleep(0.05)
            counters = self.transfer_counters(node)
        return counters

    def teardown(self) -> None:
        self._proposer_stop.set()
        self._kv_stop.set()
        if self._proposer is not None and self._proposer.ident is not None:
            self._proposer.join(timeout=10)
        if self._kv_thread is not None and self._kv_thread.ident is not None:
            self._kv_thread.join(timeout=15)
        self.supervisor.teardown()


def run_mp_scenario(
    scenario: Scenario,
    seed: int = 0,
    tick_seconds: float = 0.04,
    budget_s: float = 180.0,
    max_reqs_per_client: int = 8,
    processor: str = "serial",
) -> ScenarioResult:
    """Execute one scenario against a real multi-process cluster and
    audit every invariant; violations are reported in the result, never
    raised (harness bugs still propagate)."""
    _reject_unsupported(scenario)
    result = ScenarioResult(name=scenario.name, seed=seed, passed=False)
    driver = _MpDriver(
        scenario,
        tick_seconds,
        budget_s,
        max_reqs_per_client,
        processor,
        seed=seed,
    )
    try:
        try:
            converged_ms = driver.run()
            heals = driver.heal_times_ms
            last_heal = max(heals) if heals else 0
            bound_ms = max(
                int(driver.scale_s(scenario.recovery_bound_ms) * 1000),
                MIN_RECOVERY_BOUND_MS,
            )
            result.counters["recovery_ms"] = converged_ms - last_heal
            check_bounded_recovery(converged_ms, last_heal, bound_ms)
            if heals:
                check_commit_resumption(
                    driver.commit_times_ms, last_heal, bound_ms
                )
            evidence = driver.evidence()
            check_no_fork(evidence)
            check_durable_prefix(evidence, driver.snapshots)
            for join in scenario.joins:
                joined_ms = driver.join_times_ms.get(join.node)
                if joined_ms is None:
                    raise InvariantViolation(
                        f"join of node {join.node} never fired inside "
                        "the run window"
                    )
                caught_ms = driver.catchup_times_ms.get(join.node)
                catchup_bound = max(
                    int(driver.scale_s(join.catchup_bound_ms) * 1000),
                    MIN_RECOVERY_BOUND_MS,
                )
                if caught_ms is not None:
                    result.counters["catchup_ms"] = caught_ms - joined_ms
                check_bounded_catchup(joined_ms, caught_ms, catchup_bound)
                # The joiner must have reached the frontier by *state
                # transfer*, not by quietly replaying live traffic — a
                # fresh process that joined mid-run has no log to replay,
                # so zero installed snapshots means the scenario proved
                # nothing about the transfer path.
                counters = driver.wait_transfer_evidence(join.node)
                installed = int(
                    counters.get("snapshots_installed", 0)
                ) + int(counters.get("snapshots_resumed_staged", 0))
                result.counters["snapshots_installed"] = installed
                if installed <= 0:
                    raise InvariantViolation(
                        f"joined node {join.node} reached the frontier "
                        "without installing a snapshot (vacuous join "
                        f"scenario; engine counters: {counters})"
                    )
            if driver.reconfig_payload is not None:
                # Dynamic membership audit: adoption actually happened
                # (vacuity guard), no two nodes ever certified divergent
                # configs at the same checkpoint, and every survivor
                # converged to the committed target config.
                (
                    checkpoint_configs,
                    final_configs,
                    adoptions,
                ) = driver.config_evidence()
                agreement = check_config_agreement(
                    checkpoint_configs, final_configs, adoptions
                )
                result.counters["reconfig_adoptions"] = adoptions
                result.counters["config_checkpoints"] = agreement[
                    "checkpoints_compared"
                ]
            if scenario.notes.get("app") == "kv":
                # The user-visible claim: reads through the KV service
                # never go backwards or observe forks, even across the
                # injected crash/partition (vacuity-guarded inside).
                # KV op budgets are deliberately NOT part of convergence
                # (they would inflate the recovery clock); the cluster is
                # still up here, so let the sessions finish first.
                if not driver._kv_done.wait(timeout=60.0):
                    raise InvariantViolation(
                        "KV sessions failed to finish their op budget "
                        "within 60s of consensus convergence"
                    )
                tally = check_linearizable_reads(driver.kv_history)
                result.counters["kv_reads"] = tally["reads"]
                result.counters["kv_writes"] = tally["writes"]
                result.counters["kv_overlaps"] = tally["overlaps"]
            if scenario.removes:
                result.counters["removed"] = len(scenario.removes)
            if driver.flood_specs:
                result.counters["flooded"] = driver.flooded
                if driver.flooded <= 0:
                    raise InvariantViolation(
                        "flood scenario injected no duplicate submissions "
                        "(vacuous)"
                    )
            if scenario.name == "retry-storm-dedup" or driver.flood_specs:
                if (
                    scenario.name == "retry-storm-dedup"
                    and driver.resubmissions == 0
                ):
                    raise InvariantViolation(
                        "the retry storm never submitted a duplicate — "
                        "the scenario proved nothing"
                    )
                # Exactly-once, strictly: neither the storm nor the flood
                # may inflate any node's log past one commit per unique
                # request.
                for state in evidence.node_states:
                    pairs = [(c, q) for c, q, _s in state.committed_reqs]
                    extra = len(pairs) - len(driver.expected)
                    if extra > 0:
                        raise InvariantViolation(
                            f"duplicate storm leaked {extra} duplicate "
                            "commits into a node's log"
                        )
                if scenario.name == "retry-storm-dedup":
                    result.counters["resubmissions"] = driver.resubmissions
            result.passed = True
        except InvariantViolation as violation:
            result.violation = str(violation)
        result.events = driver.events_fired
        result.sim_ms = driver.now_ms() if driver._start is not None else 0
        result.commits = sum(
            len(handle.commits) for handle in driver.supervisor.nodes
        )
        if driver.snapshots:
            result.counters["crashes"] = len(driver.snapshots)
    finally:
        driver.teardown()
    return result


def run_mp_campaign(
    scenarios: list | None = None,
    seed: int = 0,
    tick_seconds: float = 0.04,
    budget_s: float = 180.0,
    processor: str = "serial",
) -> CampaignResult:
    """Run a scenario list (default: the mp matrix) against real
    multi-process clusters, one at a time."""
    if scenarios is None:
        scenarios = mp_matrix()
    campaign = CampaignResult(seed=seed)
    for index, scenario in enumerate(scenarios):
        campaign.results.append(
            run_mp_scenario(
                scenario,
                seed=seed + index,
                tick_seconds=tick_seconds,
                budget_s=budget_s,
                processor=processor,
            )
        )
    return campaign
