"""Worker entrypoint: ``python -m mirbft_tpu.cluster --spec <spec.json>``.

Spawned by ``ClusterSupervisor`` (one process per consensus node); can
also be launched by hand against a hand-written spec for debugging a
single node.  See worker.py for the spec schema and boot handshake.
"""

from __future__ import annotations

import argparse
import sys

from .worker import run_worker


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mirbft_tpu.cluster",
        description="Run one mirbft-tpu consensus node (cluster worker).",
    )
    parser.add_argument(
        "--spec",
        required=True,
        help="path to the node's spec.json (written by the supervisor)",
    )
    args = parser.parse_args(argv)
    return run_worker(args.spec)


if __name__ == "__main__":
    sys.exit(main())
