"""Multi-process cluster runner: one OS process per consensus node.

The in-process drivers (testengine, chaos/live.py) share one Python
process; this package runs the real thing — N worker processes
(``python -m mirbft_tpu.cluster``) supervised over a filesystem + HTTP
handshake, with true SIGKILL crashes, restart-from-disk on a stable
port, socket-proxy partitions, and emulated WAN link latency.

- ``ClusterSupervisor`` (supervisor.py): spawn/kill/restart/teardown,
  partition control, client submission, commit tailing.
- ``worker`` (worker.py): the per-node process body.
- ``chaos_mp`` (chaos_mp.py): the ``chaos --live --cluster mp`` driver.
- ``WAN_PROFILES`` (profiles.py): lan/wan/geo link-latency presets.

Lint rule W11 confines ``subprocess``/``multiprocessing`` to this
package.
"""

from .chaos_mp import (  # noqa: F401
    MP_SMOKE_NAMES,
    mp_matrix,
    retry_storm_scenario,
    run_mp_campaign,
    run_mp_scenario,
)
from .profiles import WAN_PROFILES, profile_latency  # noqa: F401
from .supervisor import ClusterSupervisor, WorkerDied  # noqa: F401
