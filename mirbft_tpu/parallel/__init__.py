"""Multi-chip scaling: device meshes, sharded crypto, collective tallies.

The reference scales with host-level concurrency (goroutine work pools,
reference: processor.go:183-470).  The TPU-native equivalents:

- the digest batch is data-parallel across a device mesh (each chip hashes a
  shard of the preimages);
- quorum tallies (prepare/commit/ack counting, reference: sequence.go:72-73,
  client_tracker.go:1018-1026) become on-device reductions with psum across
  the mesh's node axis riding ICI.

See sharding.py; __graft_entry__.dryrun_multichip drives this path on a
virtual device mesh.
"""

from .sharding import (  # noqa: F401
    make_mesh,
    sharded_sha256,
    sharded_quorum_tally,
)
