"""Sharded crypto-plane kernels over a jax.sharding.Mesh.

Three production paths:

- ``sharded_sha256(mesh)``: the digest batch is sharded over the mesh's
  ``crypto`` axis (pure data parallelism — SHA-256 lanes are independent, so
  the only communication is the result gather XLA inserts at the end).
- ``sharded_quorum_tally(mesh)``: vote matrices are sharded over voters; the
  per-sequence tally is a psum across the axis, i.e. the quorum check runs
  as an ICI collective instead of a host loop.
- ``sharded_ed25519_verify(mesh)``: the signature batch data-parallel
  across the mesh, each chip running the 256-step verification ladder on
  its shard.

The device-resident client/ack plane (core.device_tracker) builds its
kernels over the same mesh: its dense per-client state is sharded with
``client_axis_sharding`` (each chip owns a contiguous block of clients)
and ack batches are replicated with ``replicated_sharding`` so every
shard filters the rows it owns.

Shardings are expressed with NamedSharding + explicit shard_map where the
collective matters; everything compiles identically on a CPU-device mesh
(tests, dryrun) and a real TPU pod slice.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obsv import device as _device
from ..ops.sha256 import _sha256_blocks

# jax >= 0.5 promotes shard_map to jax.shard_map (kwarg check_vma); on the
# 0.4.x line it lives in jax.experimental with the kwarg spelled check_rep.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _CHECK_OFF = {"check_vma": False}
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_OFF = {"check_rep": False}

AXIS = "crypto"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            # The default platform (e.g. a single tunneled TPU chip) may have
            # fewer devices than requested; the virtual CPU mesh
            # (--xla_force_host_platform_device_count) still lets the
            # multi-chip program compile and run.
            devices = jax.devices("cpu")
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def client_axis_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding for per-client dense state: each chip owns a
    contiguous block of clients (the ack plane's unit of locality)."""
    return NamedSharding(mesh, P(AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated placement (batch columns every shard filters)."""
    return NamedSharding(mesh, P())


def sharded_sha256(mesh: Mesh):
    """Returns fn(blocks, n_blocks) -> digest words, with the batch dimension
    sharded across the mesh.  Batch size must be a multiple of the mesh size
    (ops.batching's power-of-two buckets guarantee this for pow2 meshes).

    Uses shard_map rather than GSPMD jit: the digest is embarrassingly
    parallel over the batch, and manual partitioning skips the sharding-
    propagation pass, which is pathologically slow on the 64-round
    compression program."""

    batch_sharding = NamedSharding(mesh, P(AXIS))

    def digest_local(blocks, n_blocks):
        return _sha256_blocks(blocks, n_blocks, max_blocks=blocks.shape[1])

    @functools.partial(jax.jit, static_argnames=())
    def digest(blocks, n_blocks):
        return _shard_map(
            digest_local,
            mesh=mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=P(AXIS),
            # The scan carry starts from the replicated IV constant; varying-
            # manual-axis checking would demand a pcast for no semantic gain.
            **_CHECK_OFF,
        )(blocks, n_blocks)

    def run(blocks, n_blocks):
        # device_put numpy straight onto the mesh sharding: routing through
        # jnp.asarray first would commit the array to the *default* device
        # (possibly a TPU client unrelated to this mesh) before re-sharding.
        blocks = jax.device_put(np.asarray(blocks), batch_sharding)
        n_blocks = jax.device_put(np.asarray(n_blocks), batch_sharding)
        return digest(blocks, n_blocks)

    # Explicit fn_name: every factory's closure compiles as "run", which
    # would fold all three families into one retrace counter.
    return _device.instrument("sharded_sha256", fn_name="sharded_sha256")(run)


def sharded_quorum_tally(mesh: Mesh):
    """Returns fn(votes, threshold) -> bool mask of quorum-reaching seqs.

    ``votes`` is a (n_voters, n_seqs) int8/bool matrix, sharded across
    voters; the tally is a psum over the mesh axis so each chip contributes
    its local voters' counts and the reduction rides ICI."""

    def tally_local(votes, threshold):
        local = jnp.sum(votes.astype(jnp.int32), axis=0)
        total = jax.lax.psum(local, AXIS)
        return total >= threshold

    fn = jax.jit(
        _shard_map(
            tally_local,
            mesh=mesh,
            in_specs=(P(AXIS, None), P()),
            out_specs=P(),
        )
    )

    votes_sharding = NamedSharding(mesh, P(AXIS, None))
    replicated = NamedSharding(mesh, P())

    def run(votes, threshold):
        votes = jax.device_put(np.asarray(votes), votes_sharding)
        threshold = jax.device_put(
            np.asarray(threshold, dtype=np.int32), replicated
        )
        return fn(votes, threshold)

    return _device.instrument(
        "sharded_quorum_tally", fn_name="sharded_quorum_tally"
    )(run)


def sharded_ed25519_verify(mesh: Mesh):
    """Returns fn(s_bits, k_bits, neg_a, r_affine) -> (batch,) bool with the
    signature batch sharded across the mesh (BASELINE rung 3 at pod scale:
    each chip runs the 256-step Shamir ladder on its shard; verification is
    embarrassingly parallel, so the only communication is the result
    gather).  Batch must be a multiple of the mesh size — pack inputs with
    ops.ed25519.pack_rows(rows, batch_floor=<mesh size>) to guarantee it
    for any mesh."""
    from ..ops.ed25519 import ladder_impl

    point_spec = (P(AXIS, None),) * 4
    fn = jax.jit(
        _shard_map(
            ladder_impl,
            mesh=mesh,
            in_specs=(
                P(AXIS, None),
                P(AXIS, None),
                point_spec,
                (P(AXIS, None),) * 2,
            ),
            out_specs=P(AXIS),
            # The ladder mixes replicated curve constants into per-shard
            # state; varying-manual-axes checking would demand pcasts for
            # no semantic gain (same rationale as sharded_sha256).
            **_CHECK_OFF,
        )
    )

    row = NamedSharding(mesh, P(AXIS, None))

    def run(s_bits, k_bits, neg_a, r_affine):
        s_bits = jax.device_put(np.asarray(s_bits), row)
        k_bits = jax.device_put(np.asarray(k_bits), row)
        neg_a = tuple(jax.device_put(np.asarray(c), row) for c in neg_a)
        r_affine = tuple(jax.device_put(np.asarray(c), row) for c in r_affine)
        return fn(s_bits, k_bits, neg_a, r_affine)

    return _device.instrument(
        "sharded_ed25519_verify", fn_name="sharded_ed25519_verify"
    )(run)
