"""Recorded event logs: serialize, redact, read back, replay, diff.

Rebuild of the reference's eventlog package + testengine player
(reference: eventlog/interceptor.go:84-378, eventlog/recorderpb/recorder.proto,
testengine/player.go:91-147).  Because every state-machine input is a
serializable StateEvent (the determinism discipline), a gzip stream of
``RecordedEvent{node_id, time_ms, state_event}`` captures *everything*
needed to re-execute a run: the Player feeds a recorded log into fresh
StateMachines and must land in the identical state.  This file format is
what the mircat-equivalent CLI (mirbft_tpu.cat) and the non-determinism
finder (first_divergence) operate on.

Format: gzip member containing, per event, a varint length prefix followed
by the canonical ``wire`` encoding of RecordedEvent.  Request payloads are
redacted by default (digests identify them; the bytes themselves are
application data, reference: eventlog/interceptor.go:219-299) — redaction
does not affect replayability because digests re-enter via recorded
EventActionResults, never by re-hashing.
"""

from __future__ import annotations

import gzip
import queue
import threading
import time
import zlib
from dataclasses import dataclass, field, replace

from . import pb, wire
from .core.state_machine import StateMachine


@dataclass
class RecordedEvent:
    node_id: int = 0
    time_ms: int = 0
    state_event: pb.StateEvent | None = None


RecordedEvent._spec_ = (
    ("node_id", wire.U64),
    ("time_ms", wire.U64),
    ("state_event", wire.Nested(pb.StateEvent)),
)
wire.check_spec(RecordedEvent)


# ---------------------------------------------------------------------------
# Redaction
# ---------------------------------------------------------------------------


def redact_event(event: pb.StateEvent) -> pb.StateEvent:
    """Return a copy with request payloads emptied (digests kept).

    Covers every place request data rides a state event: proposals, inbound
    ForwardRequest msgs, and the request/verify hash-result origins
    (reference: eventlog/interceptor.go:219-299)."""
    inner = event.type
    if isinstance(inner, pb.EventPropose) and inner.request is not None:
        if not inner.request.data:
            return event
        return pb.StateEvent(
            type=pb.EventPropose(request=replace(inner.request, data=b""))
        )
    if isinstance(inner, pb.EventProposeBatch):
        if not any(r.data for r in inner.requests):
            return event
        return pb.StateEvent(
            type=pb.EventProposeBatch(
                requests=[
                    replace(r, data=b"") if r.data else r
                    for r in inner.requests
                ]
            )
        )
    if isinstance(inner, pb.EventStep) and isinstance(
        inner.msg.type if inner.msg else None, pb.ForwardRequest
    ):
        fwd = inner.msg.type
        if not fwd.request_data:
            return event
        return pb.StateEvent(
            type=pb.EventStep(
                source=inner.source,
                msg=pb.Msg(type=replace(fwd, request_data=b"")),
            )
        )
    if isinstance(inner, pb.EventStepBatch):
        if not any(
            isinstance(m.type, pb.ForwardRequest) and m.type.request_data
            for m in inner.msgs
        ):
            return event
        return pb.StateEvent(
            type=pb.EventStepBatch(
                source=inner.source,
                msgs=[
                    pb.Msg(type=replace(m.type, request_data=b""))
                    if isinstance(m.type, pb.ForwardRequest)
                    and m.type.request_data
                    else m
                    for m in inner.msgs
                ],
            )
        )
    if isinstance(inner, pb.EventActionResults):
        redacted = []
        changed = False
        for hr in inner.digests:
            origin = hr.type
            if isinstance(origin, pb.HashOriginRequest) and origin.request is not None and origin.request.data:
                origin = replace(origin, request=replace(origin.request, data=b""))
                changed = True
            elif isinstance(origin, pb.HashOriginVerifyRequest) and origin.request_data:
                origin = replace(origin, request_data=b"")
                changed = True
            redacted.append(pb.HashResult(digest=hr.digest, type=origin))
        if not changed:
            return event
        return pb.StateEvent(
            type=pb.EventActionResults(
                digests=redacted, checkpoints=inner.checkpoints
            )
        )
    return event


# ---------------------------------------------------------------------------
# Writer / Reader
# ---------------------------------------------------------------------------


def write_recorded_event(stream, recorded: RecordedEvent) -> None:
    body = wire.encode(recorded)
    stream.write(wire.encode_varint(len(body)))
    stream.write(body)


def read_recorded_events(stream):
    """Yield RecordedEvents from a raw (already-decompressed) stream."""
    buf = stream.read()
    pos = 0
    while pos < len(buf):
        size, pos = wire.decode_varint(buf, pos)
        if pos + size > len(buf):
            raise ValueError("truncated recorded event")
        yield wire.decode(RecordedEvent, buf[pos : pos + size])
        pos += size


def _read_gzip_prefix(path: str) -> bytes:
    """Decompress as much of a (possibly torn) gzip file as possible.

    zlib's decompressobj hands back everything decodable before the point
    of truncation/corruption (gzip.GzipFile instead discards its buffered
    output when the end-of-stream marker is missing)."""
    with open(path, "rb") as raw:
        data = raw.read()
    out = bytearray()
    pos = 0
    while pos < len(data):
        decomp = zlib.decompressobj(wbits=47)  # auto gzip/zlib header
        try:
            out += decomp.decompress(data[pos:])
        except zlib.error:
            break  # corrupt member; keep what we have
        if not decomp.eof or not decomp.unused_data:
            break  # torn tail, or single complete member
        pos = len(data) - len(decomp.unused_data)
    return bytes(out)


class EventLogWriter:
    """Synchronous gzip event-log writer."""

    def __init__(self, path: str, redact: bool = True):
        self.path = path
        self.redact = redact
        self._gz = gzip.open(path, "wb")

    def write(self, node_id: int, time_ms: int, event: pb.StateEvent) -> None:
        if self.redact:
            event = redact_event(event)
        self.write_recorded(
            RecordedEvent(node_id=node_id, time_ms=time_ms, state_event=event)
        )

    def write_recorded(self, recorded: RecordedEvent) -> None:
        """Write an already-redacted RecordedEvent as-is."""
        write_recorded_event(self._gz, recorded)

    def close(self) -> None:
        self._gz.close()


class Recorder:
    """Async buffered interceptor for the runtime Node (reference:
    eventlog/interceptor.go:84-217): events are queued (default depth 5000,
    drop-newest on overflow with a counter) and written by a background
    thread, so the serializer never blocks on disk.

    Use ``recorder.interceptor(node_id)`` as ``Config.event_interceptor``.
    """

    def __init__(self, path: str, redact: bool = True, buffer_size: int = 5000,
                 time_source=None):
        self._writer = EventLogWriter(path, redact=redact)
        self._queue: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._time = time_source or (lambda: int(time.time() * 1000))
        self.dropped = 0
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._drain, name="eventlog-recorder", daemon=True
        )
        self._thread.start()

    def interceptor(self, node_id: int):
        def intercept(event: pb.StateEvent) -> None:
            try:
                self._queue.put_nowait((node_id, self._time(), event))
            except queue.Full:
                self.dropped += 1

        return intercept

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            node_id, time_ms, event = item
            self._writer.write(node_id, time_ms, event)

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        self._queue.put(None)
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # Drain stalled (e.g. hung disk): leave the file open rather
            # than closing it under the writer thread, which would corrupt
            # the log mid-record.
            return
        self._writer.close()


def read_log(path: str, strict: bool = False) -> list:
    """Read a recorded log into a list of RecordedEvents.

    By default a torn tail (crashed writer, SIGKILL mid-write) yields the
    intact prefix — the whole point of the log is post-mortem debugging, so
    the reader must survive exactly the runs that died badly.  ``strict``
    raises on any truncation instead."""
    if strict:
        with gzip.open(path, "rb") as gz:
            return list(read_recorded_events(gz))
    buf = _read_gzip_prefix(path)
    events = []
    pos = 0
    while pos < len(buf):
        try:
            size, body_pos = wire.decode_varint(buf, pos)
            if body_pos + size > len(buf):
                break  # torn final record
            events.append(
                wire.decode(RecordedEvent, buf[body_pos : body_pos + size])
            )
        except ValueError:
            break  # corrupt tail; keep the intact prefix
        pos = body_pos + size
    return events


def write_log(path: str, events, redact: bool = True) -> None:
    """Write an iterable of (node_id, time_ms, pb.StateEvent) tuples."""
    writer = EventLogWriter(path, redact=redact)
    try:
        for node_id, time_ms, event in events:
            writer.write(node_id, time_ms, event)
    finally:
        writer.close()


# ---------------------------------------------------------------------------
# Player
# ---------------------------------------------------------------------------


@dataclass
class PlayedNode:
    machine: StateMachine
    applied: int = 0
    actions: list = field(default_factory=list)  # last event's Actions


class Player:
    """Replays a recorded log against fresh StateMachines (reference:
    testengine/player.go:91-147).  Events must appear in the recorded order;
    each node's machine sees exactly the inputs it saw live, so its state —
    and Status() — must be identical at every index."""

    def __init__(self, events: list, logger=None):
        self.events = events
        self.logger = logger
        self.nodes: dict[int, PlayedNode] = {}
        self.position = 0

    def node(self, node_id: int) -> PlayedNode:
        played = self.nodes.get(node_id)
        if played is None:
            played = PlayedNode(machine=StateMachine(logger=self.logger))
            self.nodes[node_id] = played
        return played

    def step(self) -> RecordedEvent | None:
        if self.position >= len(self.events):
            return None
        recorded = self.events[self.position]
        self.position += 1
        played = self.node(recorded.node_id)
        if (
            isinstance(recorded.state_event.type, pb.EventInitialize)
            and played.applied > 0
        ):
            # A second Initialize on a node is a recorded restart: the live
            # run booted a fresh StateMachine (engine restart / runtime
            # process restart), so the replay must too.
            played.machine = StateMachine(logger=self.logger)
        actions = played.machine.apply_event(recorded.state_event)
        played.applied += 1
        played.actions = actions
        return recorded

    def play(self, upto: int | None = None) -> None:
        """Apply events until the log is exhausted (or `upto` total)."""
        limit = len(self.events) if upto is None else min(upto, len(self.events))
        while self.position < limit:
            self.step()


# ---------------------------------------------------------------------------
# Non-determinism finder
# ---------------------------------------------------------------------------


def first_divergence(events_a: list, events_b: list):
    """Compare two recorded logs event-by-event; returns None when equal, or
    (index, event_a | None, event_b | None) at the first divergence
    (reference: testengine/eventlog_test.go:23-60, the disabled finder)."""
    for i, (ea, eb) in enumerate(zip(events_a, events_b)):
        if wire.encode(ea) != wire.encode(eb):
            return i, ea, eb
    if len(events_a) != len(events_b):
        i = min(len(events_a), len(events_b))
        return (
            i,
            events_a[i] if i < len(events_a) else None,
            events_b[i] if i < len(events_b) else None,
        )
    return None


# ---------------------------------------------------------------------------
# Testengine adapter
# ---------------------------------------------------------------------------


class EngineLog:
    """Adapter collecting a testengine run into RecordedEvents (and
    optionally straight to disk): pass ``.interceptor`` as the Recorder's
    interceptor kwarg."""

    def __init__(self, path: str | None = None, redact: bool = True):
        self.events: list[RecordedEvent] = []
        self.redact = redact
        self._writer = (
            EventLogWriter(path, redact=redact) if path is not None else None
        )

    def interceptor(self, node: int, time_ms: int, event: pb.StateEvent) -> None:
        if self.redact:
            event = redact_event(event)
        self.events.append(
            RecordedEvent(node_id=node, time_ms=time_ms, state_event=event)
        )
        if self._writer is not None:
            # Already redacted above; don't double-copy.
            self._writer.write_recorded(self.events[-1])

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
