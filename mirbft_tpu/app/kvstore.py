"""The replicated KV state machine.

Writes are Mir batches: a client encodes an op with ``encode_put`` /
``encode_delete`` / ``encode_cas``, submits it as an ordinary
``pb.Request`` payload, and the commit stream delivers it to
``KvStore.apply`` in the consensus order with a monotone apply index.
Apply is a pure function of (op bytes, apply_index): every replica that
applies the same ordered prefix holds byte-identical state, which is
what lets the checkpoint value bind the store's digest.

Versions ARE apply indexes: a key's version is the apply index of the
op that last wrote it.  That gives reads a total-order coordinate for
free (the linearizability checker compares versions, never wall
clocks), and gives ``cas`` a precise expected-version predicate.

Malformed op bytes apply as a deterministic no-op — a garbage payload
must not fork replicas that all agree it is garbage.
"""

from __future__ import annotations

import hashlib
import struct
import threading

_OP_PUT = 1
_OP_DELETE = 2
_OP_CAS = 3
_OP_NOOP = 4

_SNAP_MAGIC = b"MKV1"


def encode_put(key: str, value: bytes) -> bytes:
    kb = key.encode()
    return struct.pack(">BH", _OP_PUT, len(kb)) + kb + struct.pack(
        ">I", len(value)
    ) + value


def encode_delete(key: str) -> bytes:
    kb = key.encode()
    return struct.pack(">BH", _OP_DELETE, len(kb)) + kb


def encode_cas(key: str, expect_version: int, value: bytes) -> bytes:
    """Compare-and-swap on a key's *version* (0 == absent)."""
    kb = key.encode()
    return (
        struct.pack(">BH", _OP_CAS, len(kb))
        + kb
        + struct.pack(">QI", expect_version, len(value))
        + value
    )


def encode_noop() -> bytes:
    return struct.pack(">BH", _OP_NOOP, 0)


def decode_op(data: bytes) -> dict | None:
    """Decode an op payload; None for anything malformed (the apply path
    treats that as a deterministic no-op)."""
    try:
        kind, klen = struct.unpack_from(">BH", data, 0)
        off = 3
        key = data[off : off + klen].decode()
        if len(data) < off + klen:
            return None
        off += klen
        if kind == _OP_PUT:
            (vlen,) = struct.unpack_from(">I", data, off)
            off += 4
            value = data[off : off + vlen]
            if len(value) != vlen:
                return None
            return {"kind": "put", "key": key, "value": value}
        if kind == _OP_DELETE:
            return {"kind": "delete", "key": key}
        if kind == _OP_CAS:
            expect, vlen = struct.unpack_from(">QI", data, off)
            off += 12
            value = data[off : off + vlen]
            if len(value) != vlen:
                return None
            return {
                "kind": "cas",
                "key": key,
                "expect_version": expect,
                "value": value,
            }
        if kind == _OP_NOOP:
            return {"kind": "noop"}
        return None
    except (struct.error, UnicodeDecodeError):
        return None


class KvStore:
    """put/get/delete/cas over ``key -> (value, version)``.

    ``apply`` runs on the commit stream's app thread; reads come from
    service threads — the internal lock keeps the two coherent without
    the stream needing to know what the state machine stores.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict = {}  # key -> (value bytes, version int)
        self.applies = 0  # ops absorbed (replay-visible; tests assert on it)

    # -- commit stream contract ------------------------------------------

    def apply(self, client_id, req_no, seq_no, apply_index, data) -> dict:
        op = decode_op(data)
        with self._lock:
            self.applies += 1
            if op is None:
                return {"outcome": "malformed", "version": 0}
            kind = op["kind"]
            if kind == "put":
                self._data[op["key"]] = (op["value"], apply_index)
                return {"outcome": "ok", "version": apply_index}
            if kind == "delete":
                had = self._data.pop(op["key"], None)
                return {
                    "outcome": "ok" if had is not None else "not_found",
                    "version": apply_index,
                }
            if kind == "cas":
                current = self._data.get(op["key"], (b"", 0))[1]
                if current == op["expect_version"]:
                    self._data[op["key"]] = (op["value"], apply_index)
                    return {"outcome": "ok", "version": apply_index}
                return {"outcome": "cas_conflict", "version": current}
            return {"outcome": "ok", "version": 0}  # noop

    def snapshot(self) -> bytes:
        """Deterministic encoding (sorted keys) of the full store."""
        with self._lock:
            items = sorted(self._data.items())
        parts = [_SNAP_MAGIC, struct.pack(">I", len(items))]
        for key, (value, version) in items:
            kb = key.encode()
            parts.append(struct.pack(">H", len(kb)))
            parts.append(kb)
            parts.append(struct.pack(">QI", version, len(value)))
            parts.append(value)
        return b"".join(parts)

    def restore(self, blob: bytes) -> None:
        if blob[:4] != _SNAP_MAGIC:
            raise ValueError("bad kv snapshot magic")
        (count,) = struct.unpack_from(">I", blob, 4)
        off = 8
        data = {}
        for _ in range(count):
            (klen,) = struct.unpack_from(">H", blob, off)
            off += 2
            key = blob[off : off + klen].decode()
            off += klen
            version, vlen = struct.unpack_from(">QI", blob, off)
            off += 12
            data[key] = (blob[off : off + vlen], version)
            off += vlen
        with self._lock:
            self._data = data

    def digest(self) -> bytes:
        """State digest binding the checkpoint value to the full store."""
        return hashlib.sha256(self.snapshot()).digest()

    # -- read path --------------------------------------------------------

    def get(self, key: str):
        """-> (value bytes | None, version int); (None, 0) when absent."""
        with self._lock:
            entry = self._data.get(key)
        return entry if entry is not None else (None, 0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)
