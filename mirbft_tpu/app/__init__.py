"""The replicated application layer over the consensus engine.

Everything below the consensus engine orders batches; everything in this
package turns that order into a *service*:

- ``journal``  — the fsynced apply journal (moved here from chaos/live.py):
  the durable ground truth for what this node has applied, and — in
  payload mode — the local replay source between checkpoints.
- ``stream``   — the apply/commit-stream API: ordered, exactly-once-per-
  apply-index delivery of committed ops to a registered state machine,
  with a persisted applied-index and snapshot-install fast-forward.
- ``kvstore``  — the KvStore replicated state machine (put/get/delete/cas)
  with deterministic apply and snapshot encode/decode.
- ``service``  — the client-facing seam: request/response framing, the
  read path (``committed`` with a read-index barrier, ``stale``
  frontier-tagged), and the multiplexing client loadgen drives.

See docs/APP.md for the API boundary and consistency guarantees.
"""

from .journal import DurableChainLog
from .kvstore import KvStore
from .stream import AppLog, CommitStream
from .service import KvClient, KvFrontend, KvService

__all__ = [
    "AppLog",
    "CommitStream",
    "DurableChainLog",
    "KvClient",
    "KvFrontend",
    "KvService",
    "KvStore",
]
