"""The apply/commit-stream API.

``CommitStream`` is the public boundary between the consensus engine and
a replicated state machine: it implements the runtime ``Log`` contract
(``apply``/``snap``), delivers every committed op to the registered app
exactly once per **apply index** — a monotone counter over ops in the
consensus order, identical on every replica — and persists that index
*inside* the app snapshot blob so a restart (or a snapshot install via
runtime/transfer.py) resumes without re-applying or gap-applying.

Threading: ``apply`` runs on the processor's commit path and only
*enqueues* into a bounded queue; a dedicated app thread drains it and
invokes the state machine.  When the app is slow the queue fills and
``apply`` blocks — backpressure propagates into the commit stage instead
of heap growth.  ``snap`` drains the queue (checkpoints capture a
consistent prefix) and then writes one atomic blob via
``storage.write_app_state``: applied seq, apply index, journal chain and
state-machine snapshot travel together, so no crash point can leave an
applied-index that disagrees with the state it describes (the
double-apply-after-restart bug class).

The checkpoint **value** returned by ``snap`` is a digest binding the
whole blob, so the 2f+1 checkpoint certificate certifies the full app
state — an installing node verifies the received blob against the
certified value before adopting it.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import time
from collections import OrderedDict

from ..obsv import hooks
from ..obsv.bqueue import BoundedQueue
from ..runtime import storage
from ..runtime.processor import Log

_STATE_MAGIC = b"MAPP1"
_BINDING_DOMAIN = b"mirbft-app-state/1"
_KEPT_SNAPSHOTS = 4


def encode_state(applied_seq: int, applied_index: int, chain: bytes,
                 app_blob: bytes) -> bytes:
    return (
        _STATE_MAGIC
        + struct.pack(">QQI", applied_seq, applied_index, len(chain))
        + chain
        + struct.pack(">I", len(app_blob))
        + app_blob
    )


def decode_state(blob: bytes):
    """-> (applied_seq, applied_index, chain, app_blob) or None."""
    if blob[: len(_STATE_MAGIC)] != _STATE_MAGIC:
        return None
    try:
        off = len(_STATE_MAGIC)
        applied_seq, applied_index, clen = struct.unpack_from(">QQI", blob, off)
        off += 20
        chain = blob[off : off + clen]
        off += clen
        (alen,) = struct.unpack_from(">I", blob, off)
        off += 4
        app_blob = blob[off : off + alen]
        if len(chain) != clen or len(app_blob) != alen:
            return None
        return applied_seq, applied_index, chain, app_blob
    except struct.error:
        return None


def state_binding(blob: bytes) -> bytes:
    """The checkpoint value for an app-state blob: certificate-bound."""
    return hashlib.sha256(_BINDING_DOMAIN + blob).digest()


class _Waiter:
    """One write's completion handle: resolved on the app thread when the
    op applies, carrying (apply_index, state-machine result)."""

    __slots__ = ("event", "index", "result")

    def __init__(self):
        self.event = threading.Event()
        self.index = 0
        self.result = None

    def wait(self, timeout):
        if not self.event.wait(timeout):
            return None
        return self.index, self.result


class _Item:
    __slots__ = ("seq", "index", "client_id", "req_no", "data", "last")

    def __init__(self, seq, index, client_id, req_no, data, last):
        self.seq = seq
        self.index = index
        self.client_id = client_id
        self.req_no = req_no
        self.data = data
        self.last = last


_STOP = object()


class CommitStream(Log):
    def __init__(
        self,
        app,
        *,
        node_id: int = 0,
        state_path: str | None = None,
        queue_depth: int = 256,
        data_source=None,
        chain_source=None,
    ):
        self.app = app
        self.node_id = node_id
        self.state_path = state_path
        self.data_source = data_source  # callable(RequestAck) -> bytes|None
        self.chain_source = chain_source  # callable() -> journal chain
        self.queue_depth = queue_depth
        self._queue = BoundedQueue("app.apply", maxsize=queue_depth)
        self._cv = threading.Condition()
        # App-thread frontier: the exactly-once floor.
        self.applied_seq = 0
        self.applied_index = 0
        # Commit-thread frontier: ops accepted from consensus (the
        # read-index barrier target for committed reads).
        self.enqueued_seq = 0
        self.enqueued_index = 0
        self.installs = 0
        self.snapshots_taken = 0
        self._waiters: dict = {}  # (client_id, req_no) -> _Waiter
        self._snapshots: OrderedDict = OrderedDict()  # value -> blob
        self.last_snapshot_blob: bytes | None = None
        self._stopped = False
        if state_path is not None:
            blob = storage.read_app_state(state_path)
            if blob is not None:
                self._adopt_blob(blob)
        self._thread = threading.Thread(
            target=self._run, name=f"app-stream-{node_id}", daemon=True
        )
        self._thread.start()

    # -- restart / install ------------------------------------------------

    def _adopt_blob(self, blob: bytes) -> None:
        decoded = decode_state(blob)
        if decoded is None:
            raise ValueError("corrupt app-state blob")
        applied_seq, applied_index, _chain, app_blob = decoded
        self.app.restore(app_blob)
        with self._cv:
            self.applied_seq = applied_seq
            self.applied_index = applied_index
            self.enqueued_seq = applied_seq
            self.enqueued_index = applied_index
            self._cv.notify_all()
        self.last_snapshot_blob = blob
        self._snapshots[state_binding(blob)] = blob

    def replay(self, entries) -> None:
        """Re-apply journaled ops above the persisted snapshot floor —
        ``entries`` as from ``DurableChainLog.drain_replay``: the restart
        path's bridge between the last checkpoint and the crash point."""
        for seq, ops in entries:
            if seq <= self.enqueued_seq:
                continue
            self._enqueue(seq, [(cid, rno, data) for cid, rno, _dig, data in ops])

    def install(self, app_bytes: bytes, value: bytes, seq_no: int) -> bool:
        """Snapshot-install fast-forward (state transfer): verify the blob
        binds to the certified checkpoint value, then jump the applied
        index/seq to the snapshot — the skipped range is never applied."""
        if state_binding(app_bytes) != value:
            return False
        decoded = decode_state(app_bytes)
        if decoded is None:
            return False
        self.drain()
        self._adopt_blob(app_bytes)
        if self.state_path is not None:
            storage.write_app_state(self.state_path, app_bytes)
        self.installs += 1
        self._gauge()
        return True

    @staticmethod
    def chain_of(app_bytes: bytes) -> bytes | None:
        """The journal chain bound inside an app-state blob (the worker
        adopts it into the durable journal on install)."""
        decoded = decode_state(app_bytes)
        return None if decoded is None else decoded[2]

    # -- Log contract ------------------------------------------------------

    def apply(self, q_entry) -> None:
        if q_entry.seq_no <= self.enqueued_seq:
            return  # WAL replay of an already-delivered entry
        ops = []
        for ack in q_entry.requests:
            data = self.data_source(ack) if self.data_source is not None else b""
            ops.append((ack.client_id, ack.req_no, data or b""))
        self._enqueue(q_entry.seq_no, ops)

    def _enqueue(self, seq: int, ops) -> None:
        if not ops:
            # Empty batch: advance the seq frontier with a marker op.
            self._queue.put(_Item(seq, 0, None, None, b"", True))
        else:
            for pos, (client_id, req_no, data) in enumerate(ops):
                self.enqueued_index += 1
                item = _Item(
                    seq,
                    self.enqueued_index,
                    client_id,
                    req_no,
                    data,
                    pos == len(ops) - 1,
                )
                self._queue.put(item)  # blocks when full: backpressure
        self.enqueued_seq = seq

    def snap(self, network_config, clients_state) -> bytes:
        self.drain()
        chain = self.chain_source() if self.chain_source is not None else b""
        blob = encode_state(
            self.applied_seq, self.applied_index, chain, self.app.snapshot()
        )
        value = state_binding(blob)
        if self.state_path is not None:
            storage.write_app_state(self.state_path, blob)
        self.last_snapshot_blob = blob
        self._snapshots[value] = blob
        while len(self._snapshots) > _KEPT_SNAPSHOTS:
            self._snapshots.popitem(last=False)
        self.snapshots_taken += 1
        self._gauge()
        return value

    def snapshot_blob(self, value: bytes) -> bytes | None:
        """The blob whose binding is ``value`` (for note_checkpoint)."""
        return self._snapshots.get(value)

    def adopt(self, value: bytes, seq_no: int) -> None:
        """Direct chain adoption is the legacy chain-log path; a KV-mode
        install goes through ``install`` with the full blob instead."""
        raise NotImplementedError(
            "CommitStream state transfer goes through install()"
        )

    # -- app thread --------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            result = None
            if item.client_id is not None:
                result = self.app.apply(
                    item.client_id, item.req_no, item.seq, item.index, item.data
                )
            with self._cv:
                if item.client_id is not None:
                    self.applied_index = item.index
                    waiter = self._waiters.pop(
                        (item.client_id, item.req_no), None
                    )
                else:
                    waiter = None
                if item.last:
                    self.applied_seq = item.seq
                self._cv.notify_all()
            if waiter is not None:
                waiter.index = item.index
                waiter.result = result
                waiter.event.set()
            if item.last:
                self._gauge()

    def _gauge(self) -> None:
        if hooks.enabled:
            hooks.metrics.gauge("mirbft_app_applied_index").set(
                self.applied_index
            )

    # -- waiters and the read-index barrier --------------------------------

    def register_waiter(self, client_id: int, req_no: int) -> _Waiter:
        """Register *before* proposing: resolved when (client_id, req_no)
        applies.  A duplicate of an already-applied op never resolves —
        callers time out and read back instead."""
        waiter = _Waiter()
        with self._cv:
            self._waiters[(client_id, req_no)] = waiter
        return waiter

    def cancel_waiter(self, client_id: int, req_no: int) -> None:
        with self._cv:
            self._waiters.pop((client_id, req_no), None)

    def frontier(self) -> int:
        """The committed frontier: ops delivered from consensus so far.
        A committed read's barrier target — covering the read's issue
        point means every op committed before the read was issued (as
        seen by this replica) has been applied."""
        return self.enqueued_index

    def read_barrier(self, min_index: int = 0, timeout: float | None = 5.0):
        """Block until the applied index covers max(frontier-at-issue,
        ``min_index``) — the PBFT §4.1 read optimization's local wait.
        -> (ok, waited_seconds, applied_index)."""
        start = time.monotonic()
        with self._cv:
            target = max(self.enqueued_index, min_index)
            ok = self._cv.wait_for(
                lambda: self.applied_index >= target or self._stopped,
                timeout=timeout,
            )
            applied = self.applied_index
        waited = time.monotonic() - start
        if hooks.enabled:
            hooks.metrics.histogram(
                "mirbft_app_read_barrier_wait_seconds"
            ).observe(waited)
        return ok and applied >= target, waited, applied

    def drain(self, timeout: float | None = 30.0) -> bool:
        """Wait until the app thread has absorbed everything enqueued."""
        with self._cv:
            return self._cv.wait_for(
                lambda: (
                    self.applied_index >= self.enqueued_index
                    and self.applied_seq >= self.enqueued_seq
                )
                or self._stopped,
                timeout=timeout,
            )

    # -- status / lifecycle ------------------------------------------------

    def status(self) -> dict:
        with self._cv:
            return {
                "applied_seq": self.applied_seq,
                "applied_index": self.applied_index,
                "enqueued_seq": self.enqueued_seq,
                "enqueued_index": self.enqueued_index,
                "queue_len": self._queue.qsize(),
                "queue_depth": self.queue_depth,
                "waiters": len(self._waiters),
                "installs": self.installs,
                "snapshots": self.snapshots_taken,
            }

    def close(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
        self._queue.put(_STOP)
        self._thread.join(timeout=5.0)


class AppLog(Log):
    """The worker's Log in app mode: the durable journal (chaos ground
    truth, local replay source) composed with the commit stream.  On
    construction, journaled ops above the stream's persisted snapshot
    floor are replayed into the state machine — the journal fsyncs every
    apply, the snapshot bounds how much of it must be re-run."""

    def __init__(self, journal, stream: CommitStream):
        self.journal = journal
        self.stream = stream
        stream.chain_source = lambda: journal.chain
        stream.replay(journal.drain_replay(stream.applied_seq))

    @property
    def chain(self) -> bytes:
        return self.journal.chain

    @property
    def commits(self) -> list:
        return self.journal.commits

    def apply(self, q_entry) -> None:
        self.journal.apply(q_entry)
        self.stream.apply(q_entry)

    def snap(self, network_config, clients_state) -> bytes:
        self.journal.snap(network_config, clients_state)
        return self.stream.snap(network_config, clients_state)

    def install(self, app_bytes: bytes, value: bytes, seq_no: int) -> bool:
        """State-transfer install: verify + adopt blob into the stream,
        then jump the journal chain to the chain bound inside it."""
        chain = CommitStream.chain_of(app_bytes)
        if chain is None or not self.stream.install(app_bytes, value, seq_no):
            return False
        self.journal.adopt(chain, seq_no)
        return True

    def close(self) -> None:
        self.stream.close()
        self.journal.close()

    def crash(self) -> None:
        self.stream.close()
        self.journal.crash()
