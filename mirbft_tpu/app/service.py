"""The client-facing service seam.

Framing is length-prefixed JSON (4-byte big-endian length + UTF-8 body)
over a plain TCP loopback listener per worker — deliberately not the
consensus transport: clients are not replicas.  One connection carries
many in-flight ops (each frame has an ``id`` the response echoes), which
is how loadgen multiplexes millions of *logical users* over a handful of
sockets.

Write path (the Mir client contract): the **client** owns the consensus
identity — it assigns ``(client_id, req_no)`` and broadcasts the write
frame to every node (the f+1 weak-certificate quorum needs the request
everywhere), with ``want_reply`` set only toward its home node.  The
home node registers a commit-stream waiter *before* proposing, and
replies when the op applies with its apply index (the version).

Read path (PBFT §4.1 read optimization — reads skip consensus):

- ``committed``: the home node blocks the read behind the read-index
  barrier — the applied index must cover max(commit frontier at issue,
  the session's high-water index) — so a read never observes an
  uncommitted or forked prefix and a session never reads backwards.
- ``stale``: served immediately, tagged with the applied frontier.
"""

from __future__ import annotations

import json
import socket
import struct
import threading

from .. import pb
from ..obsv import hooks
from . import kvstore

_LEN = struct.Struct(">I")
_MAX_FRAME = 16 * 1024 * 1024


def send_frame(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(body)) + body)


def recv_frame(rfile) -> dict | None:
    head = rfile.read(4)
    if len(head) != 4:
        return None
    (length,) = _LEN.unpack(head)
    if length > _MAX_FRAME:
        return None
    body = rfile.read(length)
    if len(body) != length:
        return None
    return json.loads(body)


class KvFrontend:
    """Socket-independent server logic: one per node, shared by the TCP
    service and the in-process loopback session."""

    def __init__(self, stream, store, propose):
        self.stream = stream
        self.store = store
        self.propose = propose  # callable(pb.Request) -> None

    @staticmethod
    def encode_write(msg: dict) -> bytes | None:
        op = msg.get("op")
        try:
            if op == "put":
                return kvstore.encode_put(msg["key"], bytes.fromhex(msg["value"]))
            if op == "delete":
                return kvstore.encode_delete(msg["key"])
            if op == "cas":
                return kvstore.encode_cas(
                    msg["key"], int(msg["expect"]), bytes.fromhex(msg["value"])
                )
        except (KeyError, TypeError, ValueError):
            return None
        return None

    def _count_write(self, op: str, outcome: str) -> None:
        if hooks.enabled:
            hooks.metrics.counter(
                "mirbft_app_writes_total", mode=op, outcome=outcome
            ).inc()

    def _count_read(self, mode: str, outcome: str) -> None:
        if hooks.enabled:
            hooks.metrics.counter(
                "mirbft_app_reads_total", mode=mode, outcome=outcome
            ).inc()

    def execute(self, msg: dict) -> dict:
        op = msg.get("op")
        if op in ("put", "delete", "cas"):
            return self._write(msg)
        if op == "get":
            return self._read(msg)
        if op == "status":
            return {"status": "ok", "app": self.stream.status()}
        return {"status": "bad_request"}

    def _write(self, msg: dict) -> dict:
        data = self.encode_write(msg)
        if data is None:
            return {"status": "bad_request"}
        client_id = int(msg["client_id"])
        req_no = int(msg["req_no"])
        want_reply = bool(msg.get("want_reply"))
        waiter = None
        if want_reply:
            waiter = self.stream.register_waiter(client_id, req_no)
        try:
            self.propose(pb.Request(client_id=client_id, req_no=req_no, data=data))
        except Exception:
            if waiter is not None:
                self.stream.cancel_waiter(client_id, req_no)
            self._count_write(msg["op"], "rejected")
            return {"status": "rejected"}
        if waiter is None:
            return {"status": "accepted"}
        got = waiter.wait(float(msg.get("timeout", 10.0)))
        if got is None:
            self.stream.cancel_waiter(client_id, req_no)
            self._count_write(msg["op"], "timeout")
            return {"status": "timeout", "frontier": self.stream.applied_index}
        index, result = got
        outcome = (result or {}).get("outcome", "ok")
        self._count_write(msg["op"], outcome)
        return {
            "status": outcome,
            "version": (result or {}).get("version", index),
            "index": index,
            "frontier": self.stream.applied_index,
        }

    def _read(self, msg: dict) -> dict:
        mode = msg.get("mode", "committed")
        key = msg["key"]
        if mode == "committed":
            ok, _waited, frontier = self.stream.read_barrier(
                min_index=int(msg.get("min_index", 0)),
                timeout=float(msg.get("timeout", 10.0)),
            )
            if not ok:
                self._count_read(mode, "timeout")
                return {"status": "timeout", "frontier": frontier}
        else:
            mode = "stale"
            frontier = self.stream.applied_index
        value, version = self.store.get(key)
        outcome = "ok" if value is not None else "not_found"
        self._count_read(mode, outcome)
        resp = {
            "status": outcome,
            "version": version,
            "frontier": frontier,
        }
        if value is not None:
            resp["value"] = value.hex()
        return resp


class KvService:
    """The per-worker loopback TCP listener: accept loop + one reader
    thread per connection; ops that block (want_reply writes, committed
    reads) run on per-request threads so one slow barrier doesn't
    head-of-line block the other logical users on the connection."""

    def __init__(self, frontend: KvFrontend, host: str = "127.0.0.1",
                 max_inflight: int = 128):
        self.frontend = frontend
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._inflight = threading.Semaphore(max_inflight)
        self._closed = False
        self._conns: list = []
        self._accept_thread = threading.Thread(
            target=self._accept, name="kv-service-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self.address[1]

    def _accept(self) -> None:
        while not self._closed:
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            threading.Thread(
                target=self._serve, args=(conn,), name="kv-service-conn",
                daemon=True,
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        wlock = threading.Lock()

        def respond(req_id, resp):
            resp["id"] = req_id
            try:
                with wlock:
                    send_frame(conn, resp)
            except OSError:
                pass

        def handle(msg):
            try:
                resp = self.frontend.execute(msg)
            except Exception:
                resp = {"status": "error"}
            finally:
                self._inflight.release()
            respond(msg.get("id"), resp)

        try:
            while not self._closed:
                msg = recv_frame(rfile)
                if msg is None:
                    return
                self._inflight.acquire()
                threading.Thread(
                    target=handle, args=(msg,), name="kv-service-op",
                    daemon=True,
                ).start()
        except OSError:
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass


class _Conn:
    """One client->node connection with a response-dispatch thread."""

    def __init__(self, address):
        self.sock = socket.create_connection(address, timeout=5.0)
        # The timeout above bounds connect only; a timed-out blocking
        # read would wrongly kill the connection during any >5s idle gap
        # or slow commit.  Op deadlines belong to the waiters, not the
        # socket.
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.rfile = self.sock.makefile("rb")
        self.wlock = threading.Lock()
        self.pending: dict = {}  # id -> (Event, [resp])
        self.plock = threading.Lock()
        self.dead = False
        threading.Thread(
            target=self._dispatch, name="kv-client-recv", daemon=True
        ).start()

    def _dispatch(self) -> None:
        while True:
            try:
                resp = recv_frame(self.rfile)
            except (OSError, ValueError):
                resp = None
            if resp is None:
                self.dead = True
                with self.plock:
                    waiting = list(self.pending.values())
                    self.pending.clear()
                for event, _slot in waiting:
                    event.set()
                return
            with self.plock:
                entry = self.pending.pop(resp.get("id"), None)
            if entry is not None:
                entry[1].append(resp)
                entry[0].set()

    def send(self, msg: dict, expect_reply: bool):
        entry = None
        if expect_reply:
            entry = (threading.Event(), [])
            with self.plock:
                self.pending[msg["id"]] = entry
        try:
            with self.wlock:
                send_frame(self.sock, msg)
        except OSError:
            self.dead = True
            if entry is not None:
                with self.plock:
                    self.pending.pop(msg["id"], None)
            return None
        return entry

    def close(self) -> None:
        self.dead = True
        try:
            self.sock.close()
        except OSError:
            pass


class KvClient:
    """One KV session: a consensus client identity (``client_id``, its
    own req_no sequence), a home node for replies and reads, and
    broadcast connections to every node.  Tracks the session's
    high-water apply index so committed reads never go backwards even
    across a home-node change.  Ops are serial per session; run many
    sessions for concurrency."""

    def __init__(self, addresses: dict, client_id: int, home: int):
        self.addresses = dict(addresses)  # node_id -> (host, port)
        self.client_id = client_id
        self.home = home
        self.req_no = 0
        self.next_id = 0
        self.session_index = 0  # high-water apply index observed
        self._conns: dict = {}

    def _conn(self, node_id):
        conn = self._conns.get(node_id)
        if conn is not None and not conn.dead:
            return conn
        if conn is not None:
            conn.close()
            self._conns.pop(node_id, None)
        addr = self.addresses.get(node_id)
        if addr is None:
            return None
        try:
            conn = _Conn(addr)
        except OSError:
            return None
        self._conns[node_id] = conn
        return conn

    def set_addresses(self, addresses: dict) -> None:
        """Refresh endpoints (chaos restarts re-bind service ports)."""
        for node_id, addr in addresses.items():
            if self.addresses.get(node_id) != addr:
                old = self._conns.pop(node_id, None)
                if old is not None:
                    old.close()
            self.addresses[node_id] = addr

    def _next_frame_id(self) -> int:
        self.next_id += 1
        return self.next_id

    def _observe(self, resp: dict) -> None:
        for field in ("index", "version", "frontier"):
            val = resp.get(field)
            if isinstance(val, int) and val > self.session_index:
                self.session_index = val

    def _write(self, msg: dict, timeout: float) -> dict:
        # Client windows open at req_no 0 and advance in order.
        req_no = self.req_no
        self.req_no += 1
        msg.update(client_id=self.client_id, req_no=req_no, timeout=timeout)
        entry = None
        for node_id in sorted(self.addresses):
            conn = self._conn(node_id)
            if conn is None:
                continue
            frame = dict(msg)
            frame["id"] = self._next_frame_id()
            frame["want_reply"] = node_id == self.home
            got = conn.send(frame, expect_reply=node_id == self.home)
            if node_id == self.home:
                entry = got
        if entry is None:
            return {"status": "unreachable"}
        if not entry[0].wait(timeout + 1.0):
            return {"status": "timeout"}
        if not entry[1]:
            return {"status": "disconnected"}
        resp = entry[1][0]
        self._observe(resp)
        return resp

    def put(self, key: str, value: bytes, timeout: float = 10.0) -> dict:
        return self._write(
            {"op": "put", "key": key, "value": value.hex()}, timeout
        )

    def delete(self, key: str, timeout: float = 10.0) -> dict:
        return self._write({"op": "delete", "key": key}, timeout)

    def cas(self, key: str, expect_version: int, value: bytes,
            timeout: float = 10.0) -> dict:
        return self._write(
            {
                "op": "cas",
                "key": key,
                "expect": expect_version,
                "value": value.hex(),
            },
            timeout,
        )

    def get(self, key: str, mode: str = "committed",
            timeout: float = 10.0) -> dict:
        conn = self._conn(self.home)
        if conn is None:
            return {"status": "unreachable"}
        frame = {
            "op": "get",
            "key": key,
            "mode": mode,
            "min_index": self.session_index if mode == "committed" else 0,
            "timeout": timeout,
            "id": self._next_frame_id(),
        }
        entry = conn.send(frame, expect_reply=True)
        if entry is None:
            return {"status": "unreachable"}
        if not entry[0].wait(timeout + 1.0):
            return {"status": "timeout"}
        if not entry[1]:
            return {"status": "disconnected"}
        resp = entry[1][0]
        self._observe(resp)
        return resp

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
