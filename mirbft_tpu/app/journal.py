"""The durable apply journal.

``DurableChainLog`` started life in chaos/live.py as the live chaos
driver's application stand-in; now that a real application layer exists
it lives here, and the chaos driver (and the cluster worker) import it
from the app package.  Semantics are unchanged: every apply is fsynced
to an append-only JSONL file, WAL replay below the last durable seq_no
is skipped, and state-transfer adoption is its own record kind.

New here: **payload mode**.  With a ``data_source`` (the request store's
``get``), each apply record also captures the request payloads, making
the journal a self-contained local replay source: after a restart the
commit stream rebuilds the state machine from its last persisted
snapshot plus the journal records above it, without depending on the
request store still holding pruned payloads.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from .. import pb
from ..runtime.processor import Log


class DurableChainLog(Log):
    """The runtime application under chaos: a hash-chain Log whose every
    apply is fsynced to an append-only JSONL file — the live analogue of
    the testengine's per-node NodeState evidence, and the ground truth
    for the no-fork / durable-prefix audits.

    WAL replay after a restart re-delivers committed entries; applies at
    or below the last durable seq_no are skipped, so the on-disk log (and
    the exactly-once audit reading it) never records a replay twice.
    State-transfer adoption is its own record kind: the chain jumps, and
    the skipped range stays absent (adopted, not individually committed).
    """

    def __init__(
        self,
        path: str,
        node_id: int,
        on_commit=None,
        timestamps=False,
        data_source=None,
    ):
        self.path = path
        self.node_id = node_id
        self.on_commit = on_commit
        # Stamp apply records with monotonic ns (CLOCK_MONOTONIC is
        # system-wide on one host, so a loadgen process on the same
        # machine computes submit→commit latency by subtraction).
        self.timestamps = timestamps
        # Payload mode: callable(RequestAck) -> bytes | None, consulted
        # at apply time (before the request store prunes the entry).
        self.data_source = data_source
        self.chain = b""
        self.commits: list = []  # [(client_id, req_no, seq_no)]
        self.last_seq = 0
        # Records with payloads read back at load, for the commit stream
        # to replay above its snapshot floor; drained once via
        # ``drain_replay`` so the payload bytes don't live forever.
        self._pending_replay: list = []  # [(seq, [(cid, rno, digest, data)])]
        if os.path.exists(path):
            self._load()
        self._file = open(path, "ab")

    def _load(self) -> None:
        with open(self.path, "rb") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail write from a crash: ignore it
                self.chain = bytes.fromhex(rec["chain"])
                self.last_seq = rec["seq"]
                if rec["t"] == "apply":
                    for client_id, req_no, _digest in rec["reqs"]:
                        self.commits.append((client_id, req_no, rec["seq"]))
                    if "data" in rec:
                        ops = [
                            (cid, rno, bytes.fromhex(dig), bytes.fromhex(dat))
                            for (cid, rno, dig), dat in zip(
                                rec["reqs"], rec["data"]
                            )
                        ]
                        self._pending_replay.append((rec["seq"], ops))
                elif rec["t"] == "adopt":
                    # Everything below an adoption came in as one snapshot;
                    # per-entry replay records before it are superseded.
                    self._pending_replay.clear()

    def drain_replay(self, from_seq: int) -> list:
        """Return (and forget) the payload-bearing apply records above
        ``from_seq``, oldest first: the commit stream's restart replay
        source between its last persisted snapshot and the crash point."""
        out = [(seq, ops) for seq, ops in self._pending_replay if seq > from_seq]
        self._pending_replay = []
        return out

    def _record(self, rec: dict) -> None:
        self._file.write(json.dumps(rec).encode() + b"\n")
        self._file.flush()
        os.fsync(self._file.fileno())

    def apply(self, q_entry: pb.QEntry) -> None:
        if q_entry.seq_no <= self.last_seq:
            return  # WAL replay of an already-durable entry
        reqs = []
        data = []
        for ack in q_entry.requests:
            h = hashlib.sha256()
            h.update(self.chain)
            h.update(ack.digest)
            self.chain = h.digest()
            self.commits.append((ack.client_id, ack.req_no, q_entry.seq_no))
            reqs.append((ack.client_id, ack.req_no, ack.digest.hex()))
            if self.data_source is not None:
                payload = self.data_source(ack)
                data.append((payload or b"").hex())
        self.last_seq = q_entry.seq_no
        rec = {
            "t": "apply",
            "seq": q_entry.seq_no,
            "reqs": reqs,
            "chain": self.chain.hex(),
        }
        if self.data_source is not None:
            rec["data"] = data
        if self.timestamps:
            rec["ts_ns"] = time.monotonic_ns()
        self._record(rec)
        if reqs and self.on_commit is not None:
            self.on_commit(self.node_id, len(reqs))

    def adopt(self, value: bytes, seq_no: int) -> None:
        """State transfer: adopt a peer's checkpointed app state."""
        self.chain = value
        if seq_no > self.last_seq:
            self.last_seq = seq_no
        self._record({"t": "adopt", "seq": seq_no, "chain": value.hex()})

    def snap(self, network_config, clients_state) -> bytes:
        return self.chain

    def close(self) -> None:
        self._file.close()

    def crash(self) -> None:
        # Every apply already fsynced, so a crash loses nothing here; the
        # distinction matters for the WAL/reqstore, whose sync cadence is
        # the runtime's.
        self._file.close()
