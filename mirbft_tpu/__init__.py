"""mirbft_tpu: a TPU-native Byzantine-fault-tolerant atomic broadcast framework.

A ground-up rebuild of the capabilities of MirBFT (reference at
/root/reference; see SURVEY.md): the multi-leader Mir consensus protocol as a
deterministic, I/O-free protocol state machine behind an Actions→Results seam,
with the executor realized as a JAX/XLA/Pallas compute plane — batched SHA-256
digests, request verification, and quorum tallies run as vmapped TPU kernels
while the branchy protocol logic stays on the host.
"""

__version__ = "0.1.0"
