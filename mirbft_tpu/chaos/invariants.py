"""Safety and liveness invariants asserted after every chaos scenario.

All checks read only harness-side state (``NodeState.committed_reqs``,
``app_chain``) — the same evidence the reference's testengine audits —
so they hold for any Recorder configuration (manglers, planes, signed
mode) without instrumenting the protocol."""

from __future__ import annotations

from dataclasses import dataclass, field


class InvariantViolation(AssertionError):
    """A chaos invariant failed; the message names scenario evidence."""


@dataclass
class CrashSnapshot:
    """What a node had durably committed the instant it was crashed."""

    node: int
    at_ms: int
    committed: list = field(default_factory=list)  # [(client, req_no, seq)]


def committed_by_seq(committed_reqs: list) -> dict:
    """[(client, req_no, seq)] -> {seq: ((client, req_no), ...)} preserving
    the within-batch commit order."""
    by_seq: dict = {}
    for client, req_no, seq in committed_reqs:
        by_seq.setdefault(seq, []).append((client, req_no))
    return {seq: tuple(reqs) for seq, reqs in by_seq.items()}


def check_no_fork(rec) -> dict:
    """Committed prefixes agree: every sequence number committed anywhere
    was committed with identical request content (and order) everywhere it
    was committed; per node, commits are seq-ordered and no request
    commits twice.  Returns the canonical {seq: requests} map."""
    canonical: dict = {}
    owner: dict = {}
    for node in range(rec.node_count):
        reqs = rec.node_states[node].committed_reqs
        seqs = [seq for _c, _q, seq in reqs]
        if seqs != sorted(seqs):
            raise InvariantViolation(
                f"node {node} committed out of seq order: {seqs}"
            )
        pairs = [(c, q) for c, q, _s in reqs]
        if len(pairs) != len(set(pairs)):
            dupes = {p for p in pairs if pairs.count(p) > 1}
            raise InvariantViolation(
                f"node {node} committed requests twice: {sorted(dupes)}"
            )
        for seq, batch in committed_by_seq(reqs).items():
            if seq not in canonical:
                canonical[seq] = batch
                owner[seq] = node
            elif canonical[seq] != batch:
                raise InvariantViolation(
                    f"fork at seq {seq}: node {owner[seq]} committed "
                    f"{canonical[seq]}, node {node} committed {batch}"
                )
    return canonical


def check_durable_prefix(rec, snapshots: list) -> None:
    """Everything a node committed before its crash survives the replay:
    the pre-crash commit log is a strict prefix of the node's final log
    (the post-restart history *continues* it, never rewrites it)."""
    for snap in snapshots:
        final = rec.node_states[snap.node].committed_reqs
        if len(final) < len(snap.committed):
            raise InvariantViolation(
                f"node {snap.node} lost commits across restart: had "
                f"{len(snap.committed)} at crash (t={snap.at_ms}ms), "
                f"has {len(final)} after recovery"
            )
        prefix = final[: len(snap.committed)]
        if prefix != snap.committed:
            for i, (pre, post) in enumerate(zip(snap.committed, prefix)):
                if pre != post:
                    raise InvariantViolation(
                        f"node {snap.node} rewrote durable history at "
                        f"commit {i}: {pre} became {post}"
                    )


def check_full_convergence(rec) -> None:
    """Every node (including restarted ones) committed every request and
    the application hash chains agree — the end-state the drain targets."""
    total = sum(c.total_reqs for c in rec.clients.values())
    for node in range(rec.node_count):
        if rec.node_states[node].crashed:
            raise InvariantViolation(f"node {node} still down at drain end")
        got = rec.committed_at(node)
        if got < total:
            raise InvariantViolation(
                f"node {node} committed {got}/{total} requests"
            )
    chains = {rec.node_states[n].app_chain for n in range(rec.node_count)}
    if len(chains) != 1:
        raise InvariantViolation(
            f"app chains diverge across nodes: {len(chains)} distinct"
        )


def check_commit_resumption(
    commit_times_ms: list, heal_ms: int, bound_ms: int
) -> None:
    """Liveness after heal, pointwise: the cluster did not merely finish
    eventually — it *resumed committing* within ``bound_ms`` of the heal
    (or restart) instant.  ``commit_times_ms`` is every instant at which
    the total committed-request count grew (simulated ms under the
    deterministic runner, wall ms under the live driver)."""
    after = [t for t in commit_times_ms if t >= heal_ms]
    if not after:
        raise InvariantViolation(
            f"no commits at all after the heal at {heal_ms}ms"
        )
    first = min(after)
    if first - heal_ms > bound_ms:
        raise InvariantViolation(
            f"commits resumed {first - heal_ms}ms after the heal at "
            f"{heal_ms}ms (bound: {bound_ms}ms)"
        )


def check_bounded_recovery(
    completion_ms: int, last_disruption_end_ms: int, bound_ms: int
) -> None:
    """Liveness resumed: the run reached full commitment within
    ``bound_ms`` of simulated time after the last heal/restart instant."""
    lag = completion_ms - max(last_disruption_end_ms, 0)
    if lag > bound_ms:
        raise InvariantViolation(
            f"recovery took {lag}ms of simulated time after the last "
            f"disruption ended (bound: {bound_ms}ms)"
        )
