"""Safety and liveness invariants asserted after every chaos scenario.

All checks read only harness-side state (``NodeState.committed_reqs``,
``app_chain``) — the same evidence the reference's testengine audits —
so they hold for any Recorder configuration (manglers, planes, signed
mode) without instrumenting the protocol."""

from __future__ import annotations

from dataclasses import dataclass, field


class InvariantViolation(AssertionError):
    """A chaos invariant failed; the message names scenario evidence."""


@dataclass
class CrashSnapshot:
    """What a node had durably committed the instant it was crashed."""

    node: int
    at_ms: int
    committed: list = field(default_factory=list)  # [(client, req_no, seq)]


def committed_by_seq(committed_reqs: list) -> dict:
    """[(client, req_no, seq)] -> {seq: ((client, req_no), ...)} preserving
    the within-batch commit order."""
    by_seq: dict = {}
    for client, req_no, seq in committed_reqs:
        by_seq.setdefault(seq, []).append((client, req_no))
    return {seq: tuple(reqs) for seq, reqs in by_seq.items()}


def check_no_fork(rec) -> dict:
    """Committed prefixes agree: every sequence number committed anywhere
    was committed with identical request content (and order) everywhere it
    was committed; per node, commits are seq-ordered and no request
    commits twice.  Returns the canonical {seq: requests} map."""
    canonical: dict = {}
    owner: dict = {}
    for node in range(rec.node_count):
        reqs = rec.node_states[node].committed_reqs
        seqs = [seq for _c, _q, seq in reqs]
        if seqs != sorted(seqs):
            raise InvariantViolation(
                f"node {node} committed out of seq order: {seqs}"
            )
        pairs = [(c, q) for c, q, _s in reqs]
        if len(pairs) != len(set(pairs)):
            dupes = {p for p in pairs if pairs.count(p) > 1}
            raise InvariantViolation(
                f"node {node} committed requests twice: {sorted(dupes)}"
            )
        for seq, batch in committed_by_seq(reqs).items():
            if seq not in canonical:
                canonical[seq] = batch
                owner[seq] = node
            elif canonical[seq] != batch:
                raise InvariantViolation(
                    f"fork at seq {seq}: node {owner[seq]} committed "
                    f"{canonical[seq]}, node {node} committed {batch}"
                )
    return canonical


def check_durable_prefix(rec, snapshots: list) -> None:
    """Everything a node committed before its crash survives the replay:
    the pre-crash commit log is a strict prefix of the node's final log
    (the post-restart history *continues* it, never rewrites it)."""
    for snap in snapshots:
        final = rec.node_states[snap.node].committed_reqs
        if len(final) < len(snap.committed):
            raise InvariantViolation(
                f"node {snap.node} lost commits across restart: had "
                f"{len(snap.committed)} at crash (t={snap.at_ms}ms), "
                f"has {len(final)} after recovery"
            )
        prefix = final[: len(snap.committed)]
        if prefix != snap.committed:
            for i, (pre, post) in enumerate(zip(snap.committed, prefix)):
                if pre != post:
                    raise InvariantViolation(
                        f"node {snap.node} rewrote durable history at "
                        f"commit {i}: {pre} became {post}"
                    )


def check_full_convergence(rec) -> None:
    """Every node (including restarted ones) committed every request and
    the application hash chains agree — the end-state the drain targets."""
    total = sum(c.total_reqs for c in rec.clients.values())
    for node in range(rec.node_count):
        if rec.node_states[node].crashed:
            raise InvariantViolation(f"node {node} still down at drain end")
        got = rec.committed_at(node)
        if got < total:
            raise InvariantViolation(
                f"node {node} committed {got}/{total} requests"
            )
    chains = {rec.node_states[n].app_chain for n in range(rec.node_count)}
    if len(chains) != 1:
        raise InvariantViolation(
            f"app chains diverge across nodes: {len(chains)} distinct"
        )


def check_no_vector_divergence(rec) -> None:
    """The ``_FastAcks`` vector ack path provably agrees with the scalar
    reference path on every node: the shadow oracle (obsv.shadow)
    re-derives weak/strong/available membership and tick classes from the
    mirror's masks and diffs them against the live objects; trackers
    running the device ack plane (core.device_tracker) are audited the
    same way against their dense arrays.  Vacuous on nodes that never
    built either plane (the scalar path IS the reference).

    Unlike the other invariants this one reads protocol-internal state,
    not harness evidence — it is exactly the determinism precondition Mir
    assumes of its replicas, checked from the inside."""
    from ..obsv import shadow

    for node in range(rec.node_count):
        tracker = rec.machines[node].client_tracker
        if (
            getattr(tracker, "_fast", None) is None
            and getattr(tracker, "_device", None) is None
        ):
            continue
        divs = shadow.audit_tracker(tracker)
        if divs:
            first = divs[0]
            raise InvariantViolation(
                f"node {node}: vector ack path diverged from the scalar "
                f"reference in {len(divs)} place(s); first: "
                f"{first['component']} at client {first['client_id']} "
                f"req_no {first['req_no']} ({first['detail']})"
            )


def check_commit_resumption(
    commit_times_ms: list, heal_ms: int, bound_ms: int
) -> None:
    """Liveness after heal, pointwise: the cluster did not merely finish
    eventually — it *resumed committing* within ``bound_ms`` of the heal
    (or restart) instant.  ``commit_times_ms`` is every instant at which
    the total committed-request count grew (simulated ms under the
    deterministic runner, wall ms under the live driver)."""
    after = [t for t in commit_times_ms if t >= heal_ms]
    if not after:
        raise InvariantViolation(
            f"no commits at all after the heal at {heal_ms}ms"
        )
    first = min(after)
    if first - heal_ms > bound_ms:
        raise InvariantViolation(
            f"commits resumed {first - heal_ms}ms after the heal at "
            f"{heal_ms}ms (bound: {bound_ms}ms)"
        )


def check_no_fork_under_equivocation(
    rec, variants: dict, expect_suspicion: bool = False, base_epoch: int = 1
) -> dict:
    """The equivocating leader never forked the log: for every (epoch, seq)
    where victims received a conflicting Preprepare, at most one of the two
    batches committed anywhere — asserted via the per-seq content audit
    plus app-chain agreement (the chain hashes the committed digests, so a
    victim committing the variant batch would diverge even though its
    (client, req_no) pairs match the real one).  ``variants`` is the
    equivocate mangler's {(epoch, seq): (real, variant)} evidence; an empty
    map means the adversary never fired and the scenario proves nothing.
    With ``expect_suspicion`` the liar must also have been rotated out
    (the honest quorum suspected it and changed epochs)."""
    if not variants:
        raise InvariantViolation(
            "equivocation scenario rewrote no Preprepares (vacuous)"
        )
    canonical = check_no_fork(rec)
    live = [n for n in range(rec.node_count) if not rec.node_states[n].crashed]
    chains = {rec.node_states[n].app_chain for n in live}
    if len(chains) != 1:
        raise InvariantViolation(
            f"app chains diverge under equivocation ({len(chains)} distinct):"
            f" a victim committed the variant batch"
        )
    if expect_suspicion:
        # base_epoch is the epoch every run negotiates at boot (the seed
        # WAL's FEntry ends epoch 0) — suspicion evidence means moving
        # beyond it.
        epochs = [
            rec.machines[n].epoch_tracker.current_epoch.number for n in live
        ]
        if max(epochs) <= base_epoch:
            raise InvariantViolation(
                "equivocating leader was never suspected: no epoch change "
                f"(epochs {epochs}) despite {len(variants)} equivocated seqs"
            )
    return canonical


def check_censorship_liveness(
    rec,
    censored_pairs: set,
    commit_epochs: dict,
    k: int,
    expect_rotation: bool = True,
) -> None:
    """Censorship is defeated by bucket rotation: every (client_id, req_no)
    the leader censored still committed, and did so within ``k`` epoch
    rotations.  ``commit_epochs`` maps each censored pair to the rotation
    count (epochs beyond the first working epoch) observed when it first
    committed anywhere, collected by the runner as commits land.  With
    ``expect_rotation`` at least one censored request must have *needed* a
    rotation — otherwise the censor never owned a victim bucket and the
    scenario proves nothing."""
    if not censored_pairs:
        raise InvariantViolation(
            "censorship scenario suppressed no requests (vacuous)"
        )
    missing = sorted(
        pair
        for pair in censored_pairs
        if pair[1] not in rec.clients[pair[0]].committed_anywhere
    )
    if missing:
        raise InvariantViolation(
            f"censored requests never committed: {missing[:10]}"
            f"{'...' if len(missing) > 10 else ''}"
        )
    late = sorted(
        (pair, epoch)
        for pair, epoch in commit_epochs.items()
        if epoch > k
    )
    if late:
        raise InvariantViolation(
            f"censored requests took more than {k} epoch rotations to "
            f"commit: {late[:10]}"
        )
    if expect_rotation and (
        not commit_epochs or max(commit_epochs.values()) < 1
    ):
        raise InvariantViolation(
            "no censored request needed an epoch rotation to commit — the "
            "censoring leader never owned a victim bucket (vacuous scenario)"
        )


def check_corruption_rejected(rejections: int, corrupted: int) -> None:
    """Signed mode rejects 100% of in-flight corruptions: every proposal
    delivery the adversary rewrote was refused at ingress authentication —
    no more (honest traffic passes) and no fewer (nothing slips through).
    Engine-agnostic: the deterministic runner passes the Recorder's
    ``byzantine_rejections``, the live driver its gate counter."""
    if corrupted <= 0:
        raise InvariantViolation(
            "corruption scenario rewrote no proposals (vacuous)"
        )
    if rejections != corrupted:
        raise InvariantViolation(
            f"signed mode rejected {rejections} of {corrupted} corrupted "
            "proposal deliveries"
        )


def check_flood_bounded(
    rec, flooded: int, wal_bound: int | None = None
) -> None:
    """Duplication/stale-ack floods are absorbed: every request still
    committed exactly once per node, the request store holds at most one
    entry per distinct request (echoes deduplicated, no unbounded memory),
    and the WAL stayed within its checkpoint-truncation envelope (no
    unbounded disk).  ``flooded`` is the adversary's echo count; zero means
    the flood never fired and the scenario proves nothing."""
    if flooded <= 0:
        raise InvariantViolation("flood scenario injected no echoes (vacuous)")
    total = sum(c.total_reqs for c in rec.clients.values())
    if wal_bound is None:
        ci = rec.initial_state.config.checkpoint_interval
        # Post-truncation WAL retains the entries above the last stable
        # checkpoint: up to ~2 in-flight checkpoint windows of QEntry+PEntry
        # pairs plus epoch-change records.
        wal_bound = 10 * ci + 8 * rec.node_count + 64
    for node in range(rec.node_count):
        state = rec.node_states[node]
        pairs = [(c, q) for c, q, _s in state.committed_reqs]
        if len(pairs) != len(set(pairs)):
            dupes = sorted({p for p in pairs if pairs.count(p) > 1})
            raise InvariantViolation(
                f"flood broke exactly-once at node {node}: {dupes[:10]}"
            )
        if len(state.reqstore) > total:
            raise InvariantViolation(
                f"flood grew node {node}'s request store to "
                f"{len(state.reqstore)} entries for {total} distinct requests"
            )
        if len(state.wal) > wal_bound:
            raise InvariantViolation(
                f"flood grew node {node}'s WAL to {len(state.wal)} entries "
                f"(bound {wal_bound}): checkpoint truncation fell behind"
            )


def check_bounded_catchup(
    join_ms: int, frontier_ms: int | None, bound_ms: int
) -> None:
    """A freshly joined (or far-behind) node reached the cluster's commit
    frontier — via checkpoint-anchored snapshot state transfer — within
    ``bound_ms`` of its join instant.  ``frontier_ms`` is the wall (or
    simulated) instant the joiner first held the certified checkpoint
    state; ``None`` means it never caught up."""
    if frontier_ms is None:
        raise InvariantViolation(
            f"joined node never reached the commit frontier (joined at "
            f"{join_ms}ms)"
        )
    lag = frontier_ms - join_ms
    if lag > bound_ms:
        raise InvariantViolation(
            f"joined node took {lag}ms after joining at {join_ms}ms to "
            f"reach the commit frontier (bound: {bound_ms}ms)"
        )


def check_transfer_corruption_rejected(
    rejections: int, corrupted: int
) -> None:
    """Snapshot-transfer streams the adversary corrupted/truncated were
    refused by the fetcher's digest-chain and certificate verification.
    ``corrupted`` is the proxy manglers' touch count (zero = vacuous),
    ``rejections`` the engines' ``chunks_rejected_corrupt`` evidence.
    Mangled frames arriving outside an active fetch are dropped
    unattributed (stale) rather than rejected-with-evidence, so the
    audit demands rejection evidence exists rather than exact equality;
    the none-was-*adopted* half is held by the no-fork / chain-agreement
    audits, which a single accepted corrupt chunk would break."""
    if corrupted <= 0:
        raise InvariantViolation(
            "transfer-corruption scenario touched no frames (vacuous)"
        )
    if rejections <= 0:
        raise InvariantViolation(
            f"{corrupted} corrupted transfer frames produced no "
            "rejection evidence (chunks_rejected_corrupt == 0)"
        )


def check_mac_rejected(
    rejections: int, forged: int, exact: bool = True
) -> None:
    """MAC-authenticated replica channels reject 100% of forged/tampered
    node-to-node traffic.  ``forged`` is the adversary's touch count
    (zero = vacuous); ``rejections`` the MAC layer's evidence — the
    deterministic MacSealPlane's counter or the live transports'
    ``mac_rejections`` sum.

    The deterministic engine delivers every forged message exactly once,
    so the audit demands exact equality (``exact=True``: no more —
    honest traffic passes — and no fewer — nothing slips through).  On
    the live transport a forged frame can die with its TCP connection
    before reaching the receiver (reconnects, shutdown races), so the
    live audit demands rejection evidence exists and never exceeds the
    forgery count; the none-was-*accepted* half is held by the no-fork /
    convergence audits, which a single admitted forgery would break."""
    if forged <= 0:
        raise InvariantViolation(
            "MAC-forgery scenario touched no replica frames (vacuous)"
        )
    if exact and rejections != forged:
        raise InvariantViolation(
            f"MAC layer rejected {rejections} of {forged} forged replica "
            "messages"
        )
    if not exact:
        if rejections <= 0:
            raise InvariantViolation(
                f"{forged} forged replica frames produced no MAC "
                "rejection evidence (mac_rejections == 0)"
            )
        if rejections > forged:
            raise InvariantViolation(
                f"MAC layer rejected {rejections} frames but the "
                f"adversary only forged {forged} — honest traffic was "
                "refused"
            )


def check_aggregate_cert_rejected(
    genuine_ok: int,
    genuine_total: int,
    forged_rejected: int,
    forged_total: int,
) -> None:
    """Aggregate quorum certificates are sound both ways: every genuine
    certificate the cluster produced verifies under one aggregate check,
    and every forged variant (mismatched statement, wrong signer set) is
    rejected — 100%, with vacuity guards on both sides."""
    if genuine_total <= 0:
        raise InvariantViolation(
            "certificate audit saw no quorum certificates (vacuous — the "
            "run never reached a stable checkpoint)"
        )
    if genuine_ok != genuine_total:
        raise InvariantViolation(
            f"only {genuine_ok} of {genuine_total} genuine aggregate "
            "certificates verified"
        )
    if forged_total <= 0:
        raise InvariantViolation(
            "certificate audit built no forged variants (vacuous)"
        )
    if forged_rejected != forged_total:
        raise InvariantViolation(
            f"only {forged_rejected} of {forged_total} forged aggregate "
            "certificates were rejected"
        )


def audit_aggregate_certs(certs: dict) -> tuple:
    """Exercise the qc seam over a run's quorum certificates:
    ``certs`` maps (seq_no, value) -> (signer ids, aggregate signature)
    (CheckpointCertPlane.certificates(), or the live synthesis).  Every
    genuine certificate must verify; per certificate two forgeries are
    attempted — a mismatched statement (wrong seq_no under a valid
    aggregate) and a wrong signer set (aggregate public key excludes a
    voter) — and must fail.  Returns
    ``(genuine_ok, genuine_total, forged_rejected, forged_total)`` for
    :func:`check_aggregate_cert_rejected`."""
    from ..testengine.certs import CheckpointCertPlane, node_seed, statement
    from ..crypto import qc

    genuine_ok = forged_rejected = forged_total = 0
    for (seq_no, value), (signers, asig) in certs.items():
        if CheckpointCertPlane.verify(seq_no, value, signers, asig):
            genuine_ok += 1
        # Forgery 1: valid aggregate, mismatched statement.
        forged_total += 1
        if not CheckpointCertPlane.verify(seq_no + 1, value, signers, asig):
            forged_rejected += 1
        # Forgery 2: wrong signer set — the aggregate public key drops
        # one voter and claims a non-voter instead.
        forged_total += 1
        imposter = max(signers) + 1
        wrong = list(signers[1:]) + [imposter]
        pks = [qc.public_key(node_seed(n)) for n in wrong]
        if not qc.verify_cert(pks, statement(seq_no, value), asig):
            forged_rejected += 1
    return genuine_ok, len(certs), forged_rejected, forged_total


def check_bounded_recovery(
    completion_ms: int, last_disruption_end_ms: int, bound_ms: int
) -> None:
    """Liveness resumed: the run reached full commitment within
    ``bound_ms`` of simulated time after the last heal/restart instant."""
    lag = completion_ms - max(last_disruption_end_ms, 0)
    if lag > bound_ms:
        raise InvariantViolation(
            f"recovery took {lag}ms of simulated time after the last "
            f"disruption ended (bound: {bound_ms}ms)"
        )


def check_config_agreement(
    checkpoint_configs: dict, final_configs: dict, adoptions: int
) -> dict:
    """Dynamic membership never splits the configuration: no two correct
    nodes certify divergent network configs at the same checkpoint
    sequence number, and every correct survivor converges to the same
    final active config.

    Engine-agnostic evidence:

    - ``checkpoint_configs``: {node: {seq_no: config_bytes}} — the
      ``pb.encode``'d NetworkConfig each node bound into its checkpoint
      at each stable seq (the deterministic runner reads
      ``NodeState.checkpoints``, the live driver each worker's
      checkpoints.jsonl).
    - ``final_configs``: {node: config_bytes} — each correct survivor's
      active config at drain end.
    - ``adoptions``: total reconfiguration-adoption events observed
      across nodes (``reconfigs_adopted`` / reconfig.json evidence).

    Vacuity guard: at least one adoption must have been observed —
    otherwise no reconfiguration ever activated and agreement is
    trivially true.  Returns tally evidence."""
    if adoptions < 1:
        raise InvariantViolation(
            "reconfig scenario adopted no reconfiguration (vacuous): "
            "config agreement proves nothing"
        )
    canonical: dict = {}  # seq -> (config_bytes, node)
    compared = 0
    for node in sorted(checkpoint_configs):
        for seq, config in sorted(checkpoint_configs[node].items()):
            prior = canonical.get(seq)
            if prior is None:
                canonical[seq] = (config, node)
            else:
                compared += 1
                if prior[0] != config:
                    raise InvariantViolation(
                        f"config fork at checkpoint seq {seq}: node "
                        f"{prior[1]} certified {prior[0].hex()}, node "
                        f"{node} certified {config.hex()}"
                    )
    finals = {}
    for node, config in sorted(final_configs.items()):
        finals.setdefault(config, []).append(node)
    if len(finals) > 1:
        groups = {
            cfg.hex(): nodes for cfg, nodes in sorted(finals.items())
        }
        raise InvariantViolation(
            f"correct survivors diverge on the final active config: "
            f"{groups}"
        )
    return {
        "adoptions": adoptions,
        "checkpoints_compared": compared,
        "survivors": len(final_configs),
    }


def check_linearizable_reads(history: list) -> dict:
    """Reads over the replicated KV never go backwards or observe forks.

    ``history`` is the KV workload's op record: dicts with ``client_id``,
    ``op`` ("get"/"put"), ``key``, ``invoke_ns``/``return_ns`` wall
    intervals, ``outcome``, ``version`` (the apply index that stamped
    the value), and ``value`` (hex) for successful ops.

    The audit is Wing&Gong-shaped but deliberately checks the decidable
    core rather than brute-force linearization search:

    - **version functionality (no forks)**: a (key, version) pair maps
      to exactly one value across every op that observed it — two
      different values under one version means diverged replicas.
    - **write-version uniqueness**: versions are apply indexes, so two
      acknowledged writes can never share a (key, version).
    - **per-session monotonic reads**: within one client session,
      successive reads of a key never observe a version older than a
      version that session already observed for it.
    - **read-your-writes**: a read issued after the same session's
      acknowledged write to that key must observe that write's version
      or newer (the write raises the session's version floor).

    Vacuity guard: the history must contain at least one read/write
    pair on the same key whose intervals overlap — otherwise the run
    never exercised read/write concurrency and a pass proves nothing.
    Returns tally evidence ``{"reads": n, "writes": n, "overlaps": n}``.
    """
    reads = [
        h for h in history if h["op"] == "get" and h["outcome"] == "ok"
    ]
    all_reads = [h for h in history if h["op"] == "get"]
    writes = [
        h for h in history if h["op"] != "get" and h["outcome"] == "ok"
    ]
    if not all_reads or not writes:
        raise InvariantViolation(
            f"KV history is vacuous: {len(all_reads)} reads / "
            f"{len(writes)} acknowledged writes"
        )

    overlaps = 0
    writes_by_key: dict = {}
    for w in writes:
        writes_by_key.setdefault(w["key"], []).append(w)
    for r in all_reads:
        for w in writes_by_key.get(r["key"], ()):
            if (
                r["invoke_ns"] < w["return_ns"]
                and w["invoke_ns"] < r["return_ns"]
            ):
                overlaps += 1
                break
    if overlaps == 0:
        raise InvariantViolation(
            "KV history is vacuous: no read's interval overlaps any "
            "write to the same key"
        )

    # Version functionality: one value per (key, version), everywhere.
    observed: dict = {}  # (key, version) -> (value_hex, who)
    for h in writes + reads:
        version = h.get("version", 0)
        value = h.get("value")
        if not version or value is None:
            continue
        prior = observed.get((h["key"], version))
        if prior is None:
            observed[(h["key"], version)] = (value, h)
        elif prior[0] != value:
            raise InvariantViolation(
                f"fork: key {h['key']!r} version {version} observed as "
                f"{prior[0]!r} (client {prior[1]['client_id']}) and "
                f"{value!r} (client {h['client_id']})"
            )

    # Write-version uniqueness.
    write_versions: dict = {}  # (key, version) -> write
    for w in writes:
        version = w.get("version", 0)
        if not version:
            continue
        prior = write_versions.get((w["key"], version))
        if prior is not None:
            raise InvariantViolation(
                f"two acknowledged writes share key {w['key']!r} "
                f"version {version} (clients {prior['client_id']} "
                f"and {w['client_id']})"
            )
        write_versions[(w["key"], version)] = w

    # Per-session ordering: monotonic reads + read-your-writes.
    by_session: dict = {}
    for h in history:
        by_session.setdefault(h["client_id"], []).append(h)
    for client_id, ops in by_session.items():
        ops.sort(key=lambda h: h["invoke_ns"])
        floor: dict = {}  # key -> highest version this session observed
        for h in ops:
            version = h.get("version", 0)
            if h["op"] == "get":
                if h["outcome"] != "ok":
                    continue
                prior = floor.get(h["key"], 0)
                if version < prior:
                    raise InvariantViolation(
                        f"session {client_id} read of {h['key']!r} went "
                        f"backwards: observed version {version} after "
                        f"{prior}"
                    )
                floor[h["key"]] = max(prior, version)
            elif h["outcome"] == "ok" and version:
                floor[h["key"]] = max(floor.get(h["key"], 0), version)

    return {
        "reads": len(all_reads),
        "writes": len(writes),
        "overlaps": overlaps,
    }
