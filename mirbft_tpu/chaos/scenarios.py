"""The chaos scenario matrix.

Each ``Scenario`` is declarative: factories (not instances) for manglers
and crypto planes, because both are stateful per run — the runner builds
fresh ones for every (scenario, seed) execution so campaigns are
reproducible and scenarios can repeat across seeds.

The matrix mirrors the reference's fault suite (mirbft_test.go:68-222)
and extends it with network partitions (with heal) and device-plane
faults against the coalescing crypto planes."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..resilience import CircuitBreaker
from ..testengine.crypto_plane import CoalescingHashPlane
from ..testengine.manglers import (
    from_source,
    is_step,
    msg_type,
    partition,
    percent,
    rule,
)
from .faults import FlakyDigestBackend


@dataclass(frozen=True)
class CrashPoint:
    """Runner-driven crash: at ``at_ms`` simulated time, crash ``node``
    (snapshotting its durable commit log for the durability invariant)
    and reboot it from durable state ``restart_delay_ms`` later."""

    at_ms: int
    node: int
    restart_delay_ms: int


@dataclass
class Scenario:
    name: str
    description: str = ""
    tags: tuple = ()
    node_count: int = 4
    client_count: int = 2
    reqs_per_client: int = 10
    batch_size: int = 1
    # Zero-arg factory -> list of manglers (fresh state per run).
    manglers: object = None
    crashes: tuple = ()  # CrashPoints, fired by the runner
    # Zero-arg factory -> hash plane (fresh breaker/counters per run).
    hash_plane: object = None
    # Heal instants (ms) of disruptions the manglers inject (partition
    # until_ms etc.); restarts from ``crashes`` are added automatically.
    heal_points_ms: tuple = ()
    recovery_bound_ms: int = 120_000
    max_steps: int = 600_000
    notes: dict = field(default_factory=dict)

    def disruption_ends(self) -> list:
        ends = list(self.heal_points_ms)
        ends.extend(c.at_ms + c.restart_delay_ms for c in self.crashes)
        return ends


def _flaky_plane(mode: str, **kwargs):
    """Factory-factory: a CoalescingHashPlane whose backend misbehaves for
    a call window, guarded by a hair-trigger breaker.

    The lazy plane coalesces a whole run into ~4 backend calls, so the
    window ``fail_from=1, fail_until=3`` with threshold/probe of 1 walks
    the breaker through its full lifecycle deterministically: call 0
    healthy, call 1 fails (trip → open), call 2 is a probe and fails
    (re-open), call 3 is a probe and succeeds (re-close)."""

    def build():
        return CoalescingHashPlane(
            digest_many=FlakyDigestBackend(mode=mode, **kwargs),
            breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
            timeout_s=0.0005 if mode == "slow" else None,
        )

    return build


def matrix() -> list:
    """The full campaign: baseline, the reference fault suite, partitions
    with heal, crash schedules, device-plane faults, and combinations."""
    return [
        Scenario(
            name="baseline",
            description="no faults; anchors event counts for the seed",
        ),
        Scenario(
            name="jitter-30ms",
            description="30ms delivery jitter on every message",
            manglers=lambda: [rule(is_step()).jitter(30)],
        ),
        Scenario(
            name="jitter-1000ms",
            description="1000ms delivery jitter (reorders across ticks)",
            manglers=lambda: [rule(is_step()).jitter(1000)],
        ),
        Scenario(
            name="duplicate-75pct",
            description="75% of messages delivered twice (delayed echo)",
            manglers=lambda: [rule(is_step(), percent(75)).duplicate(300)],
        ),
        Scenario(
            name="drop-10pct",
            description="10% uniform message loss",
            manglers=lambda: [rule(is_step(), percent(10)).drop()],
        ),
        Scenario(
            name="ack-loss-70pct",
            description="70% RequestAck loss from nodes 1 and 2",
            manglers=lambda: [
                rule(msg_type("RequestAck"), from_source(1, 2), percent(70))
                .drop()
            ],
        ),
        Scenario(
            name="partition-minority",
            description="node 0 isolated 2s..12s, then heals",
            manglers=lambda: [
                partition([[0], [1, 2, 3]], from_ms=2000, until_ms=12_000)
            ],
            heal_points_ms=(12_000,),
        ),
        Scenario(
            name="partition-split-2-2",
            description="2-2 split (no quorum anywhere) 2s..10s, then heals",
            manglers=lambda: [
                partition([[0, 1], [2, 3]], from_ms=2000, until_ms=10_000)
            ],
            heal_points_ms=(10_000,),
        ),
        Scenario(
            name="partition-flapping",
            description="node 3 isolated twice: 2s..6s and 9s..13s",
            manglers=lambda: [
                partition([[3], [0, 1, 2]], from_ms=2000, until_ms=6000),
                partition([[3], [0, 1, 2]], from_ms=9000, until_ms=13_000),
            ],
            heal_points_ms=(6000, 13_000),
        ),
        Scenario(
            name="crash-restart",
            description="node 1 crashes at 3s, reboots from WAL 5s later",
            crashes=(CrashPoint(at_ms=3000, node=1, restart_delay_ms=5000),),
        ),
        Scenario(
            name="crash-staggered-pair",
            description="nodes 1 and 2 crash/restart at staggered times "
            "(never below quorum simultaneously)",
            crashes=(
                CrashPoint(at_ms=3000, node=1, restart_delay_ms=5000),
                CrashPoint(at_ms=12_000, node=2, restart_delay_ms=5000),
            ),
        ),
        Scenario(
            name="device-digest-dies",
            description="digest device raises mid-run; breaker trips to "
            "host oracle, then a probe re-closes it",
            hash_plane=_flaky_plane("die", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="device-digest-short-read",
            description="digest device returns half a batch (lying "
            "readback); plane recomputes on host",
            hash_plane=_flaky_plane("short", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="device-digest-hangs",
            description="digest device exceeds its deadline for a window; "
            "timeouts trip the breaker",
            hash_plane=_flaky_plane("slow", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="partition-plus-crash",
            description="node 0 isolated 2s..10s while node 2 crashes at "
            "4s and reboots at 9s",
            manglers=lambda: [
                partition([[0], [1, 2, 3]], from_ms=2000, until_ms=10_000)
            ],
            crashes=(CrashPoint(at_ms=4000, node=2, restart_delay_ms=5000),),
            heal_points_ms=(10_000,),
        ),
        Scenario(
            name="partition-plus-duplication",
            description="2-2 split 2s..8s under 50% duplication",
            manglers=lambda: [
                partition([[0, 1], [2, 3]], from_ms=2000, until_ms=8000),
                rule(is_step(), percent(50)).duplicate(300),
            ],
            heal_points_ms=(8000,),
        ),
    ]


# The tier-1 smoke subset: one partition-with-heal, one crash-with-
# restart, one device-plane failure — the three disruption families.
SMOKE_NAMES = ("partition-minority", "crash-restart", "device-digest-dies")


def smoke_matrix() -> list:
    by_name = {s.name: s for s in matrix()}
    return [by_name[name] for name in SMOKE_NAMES]
