"""The chaos scenario matrix: one schema, two engines.

Each ``Scenario`` is declarative: factories (not instances) for manglers
and crypto planes, because both are stateful per run — the runner builds
fresh ones for every (scenario, seed) execution so campaigns are
reproducible and scenarios can repeat across seeds.

The structured fault fields — ``partitions`` (PartitionWindow),
``crashes`` (CrashPoint), ``drop_pct``, ``storage_faults``
(StorageFault), ``signed`` — are engine-agnostic: the deterministic
runner (runner.py) lowers them onto testengine manglers and simulated
crash schedules, while the live driver (live.py) lowers the *same*
scenario onto socket-level partition proxies, real crash-kills of
runtime Nodes, transport-seam message loss, and failing fsyncs.  Only
``manglers`` (the raw mangler-DSL escape hatch) is testengine-specific.

The matrix mirrors the reference's fault suite (mirbft_test.go:68-222)
and extends it with network partitions (with heal), device-plane faults
against the coalescing crypto planes, epoch-change-targeted leader
isolation, and signed-mode verifier faults."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..resilience import CircuitBreaker
from ..testengine.crypto_plane import CoalescingHashPlane
from ..testengine.manglers import (
    from_source,
    is_step,
    msg_type,
    partition,
    percent,
    rule,
)
from ..testengine.signing import SignaturePlane
from .faults import FlakyDigestBackend, FlakyVerifierBackend


@dataclass(frozen=True)
class CrashPoint:
    """Runner-driven crash: at ``at_ms`` simulated time, crash ``node``
    (snapshotting its durable commit log for the durability invariant)
    and reboot it from durable state ``restart_delay_ms`` later."""

    at_ms: int
    node: int
    restart_delay_ms: int


@dataclass(frozen=True)
class PartitionWindow:
    """Declarative network split: messages crossing between ``groups``
    are cut for ``from_ms <= t < until_ms``, then the network heals.
    The deterministic runner lowers this onto the partition() mangler;
    the live driver cuts the socket-level partition proxies."""

    groups: tuple  # tuple of tuples of node ids, covering all nodes
    from_ms: int
    until_ms: int


@dataclass(frozen=True)
class StorageFault:
    """Live-only fault: from ``at_ms`` the node's WAL/reqstore fsyncs
    raise OSError, so the runtime's persist path fails loudly; the
    driver crash-kills the node and reboots it — with healthy storage —
    ``restart_delay_ms`` after the fault hit."""

    at_ms: int
    node: int
    restart_delay_ms: int


@dataclass
class Scenario:
    name: str
    description: str = ""
    tags: tuple = ()
    node_count: int = 4
    client_count: int = 2
    reqs_per_client: int = 10
    batch_size: int = 1
    # Zero-arg factory -> list of manglers (fresh state per run).
    # Testengine-only: prefer the structured fields below, which both
    # engines understand.
    manglers: object = None
    crashes: tuple = ()  # CrashPoints, fired by the runner
    partitions: tuple = ()  # PartitionWindows (both engines)
    drop_pct: int = 0  # uniform message-loss percentage (both engines)
    storage_faults: tuple = ()  # StorageFaults (live driver only)
    # Signed-request mode: clients Ed25519-sign, replicas verify at
    # ingress through a SignaturePlane (factory below, fresh per run).
    signed: bool = False
    signature_plane: object = None  # zero-arg factory (signed mode)
    # The scenario is designed to force an epoch change; the runner
    # fails it unless every surviving node ends in an epoch >= 1.
    expect_epoch_change: bool = False
    # Zero-arg factory -> hash plane (fresh breaker/counters per run).
    hash_plane: object = None
    # Heal instants (ms) of disruptions the raw manglers inject;
    # structured faults (partitions/crashes/storage) are added
    # automatically by disruption_ends().
    heal_points_ms: tuple = ()
    recovery_bound_ms: int = 120_000
    max_steps: int = 600_000
    notes: dict = field(default_factory=dict)

    def disruption_ends(self) -> list:
        ends = list(self.heal_points_ms)
        ends.extend(w.until_ms for w in self.partitions)
        ends.extend(c.at_ms + c.restart_delay_ms for c in self.crashes)
        ends.extend(s.at_ms + s.restart_delay_ms for s in self.storage_faults)
        return ends

    def build_manglers(self) -> list:
        """Lower the structured fault fields onto testengine manglers
        (plus any raw ``manglers`` the scenario carries).  Fresh mangler
        state per call, so runs stay independent."""
        built = []
        for window in self.partitions:
            built.append(
                partition(
                    [list(group) for group in window.groups],
                    from_ms=window.from_ms,
                    until_ms=window.until_ms,
                )
            )
        if self.drop_pct:
            built.append(rule(is_step(), percent(self.drop_pct)).drop())
        if self.manglers:
            built.extend(self.manglers())
        return built


def _flaky_plane(mode: str, **kwargs):
    """Factory-factory: a CoalescingHashPlane whose backend misbehaves for
    a call window, guarded by a hair-trigger breaker.

    The lazy plane coalesces a whole run into ~4 backend calls, so the
    window ``fail_from=1, fail_until=3`` with threshold/probe of 1 walks
    the breaker through its full lifecycle deterministically: call 0
    healthy, call 1 fails (trip → open), call 2 is a probe and fails
    (re-open), call 3 is a probe and succeeds (re-close)."""

    def build():
        return CoalescingHashPlane(
            digest_many=FlakyDigestBackend(mode=mode, **kwargs),
            breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
            timeout_s=0.0005 if mode == "slow" else None,
        )

    return build


def _flaky_signature_plane(**kwargs):
    """Factory-factory: a SignaturePlane whose verifier backend
    misbehaves for a call window, guarded by the same hair-trigger
    breaker as _flaky_plane so the trip → fallback → probe → re-close
    cycle is walked deterministically."""

    def build():
        return SignaturePlane(
            verifier=FlakyVerifierBackend(**kwargs),
            breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
        )

    return build


def matrix() -> list:
    """The full campaign: baseline, the reference fault suite, partitions
    with heal, crash schedules, device-plane faults, and combinations."""
    return [
        Scenario(
            name="baseline",
            description="no faults; anchors event counts for the seed",
        ),
        Scenario(
            name="jitter-30ms",
            description="30ms delivery jitter on every message",
            manglers=lambda: [rule(is_step()).jitter(30)],
        ),
        Scenario(
            name="jitter-1000ms",
            description="1000ms delivery jitter (reorders across ticks)",
            manglers=lambda: [rule(is_step()).jitter(1000)],
        ),
        Scenario(
            name="duplicate-75pct",
            description="75% of messages delivered twice (delayed echo)",
            manglers=lambda: [rule(is_step(), percent(75)).duplicate(300)],
        ),
        Scenario(
            name="drop-10pct",
            description="10% uniform message loss",
            drop_pct=10,
        ),
        Scenario(
            name="ack-loss-70pct",
            description="70% RequestAck loss from nodes 1 and 2",
            manglers=lambda: [
                rule(msg_type("RequestAck"), from_source(1, 2), percent(70))
                .drop()
            ],
        ),
        Scenario(
            name="partition-minority",
            description="node 0 isolated 2s..12s, then heals",
            partitions=(
                PartitionWindow(
                    groups=((0,), (1, 2, 3)), from_ms=2000, until_ms=12_000
                ),
            ),
        ),
        Scenario(
            name="partition-split-2-2",
            description="2-2 split (no quorum anywhere) 2s..10s, then heals",
            partitions=(
                PartitionWindow(
                    groups=((0, 1), (2, 3)), from_ms=2000, until_ms=10_000
                ),
            ),
        ),
        Scenario(
            name="partition-flapping",
            description="node 3 isolated twice: 2s..6s and 9s..13s",
            partitions=(
                PartitionWindow(
                    groups=((3,), (0, 1, 2)), from_ms=2000, until_ms=6000
                ),
                PartitionWindow(
                    groups=((3,), (0, 1, 2)), from_ms=9000, until_ms=13_000
                ),
            ),
        ),
        Scenario(
            name="crash-restart",
            description="node 1 crashes at 3s, reboots from WAL 5s later",
            crashes=(CrashPoint(at_ms=3000, node=1, restart_delay_ms=5000),),
        ),
        Scenario(
            name="crash-staggered-pair",
            description="nodes 1 and 2 crash/restart at staggered times "
            "(never below quorum simultaneously)",
            crashes=(
                CrashPoint(at_ms=3000, node=1, restart_delay_ms=5000),
                CrashPoint(at_ms=12_000, node=2, restart_delay_ms=5000),
            ),
        ),
        Scenario(
            name="device-digest-dies",
            description="digest device raises mid-run; breaker trips to "
            "host oracle, then a probe re-closes it",
            hash_plane=_flaky_plane("die", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="device-digest-short-read",
            description="digest device returns half a batch (lying "
            "readback); plane recomputes on host",
            hash_plane=_flaky_plane("short", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="device-digest-hangs",
            description="digest device exceeds its deadline for a window; "
            "timeouts trip the breaker",
            hash_plane=_flaky_plane("slow", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="partition-plus-crash",
            description="node 0 isolated 2s..10s while node 2 crashes at "
            "4s and reboots at 9s",
            partitions=(
                PartitionWindow(
                    groups=((0,), (1, 2, 3)), from_ms=2000, until_ms=10_000
                ),
            ),
            crashes=(CrashPoint(at_ms=4000, node=2, restart_delay_ms=5000),),
        ),
        Scenario(
            name="partition-plus-duplication",
            description="2-2 split 2s..8s under 50% duplication",
            partitions=(
                PartitionWindow(
                    groups=((0, 1), (2, 3)), from_ms=2000, until_ms=8000
                ),
            ),
            manglers=lambda: [rule(is_step(), percent(50)).duplicate(300)],
        ),
        Scenario(
            name="leader-isolation-epoch-change",
            description="node 0 (a leader) isolated 2s..20s under 5% loss "
            "— held far past the suspect timeout, so the survivors must "
            "change epochs and commit the suspect's in-flight sequences "
            "exactly once",
            partitions=(
                PartitionWindow(
                    groups=((0,), (1, 2, 3)), from_ms=2000, until_ms=20_000
                ),
            ),
            drop_pct=5,
            expect_epoch_change=True,
            tags=("epoch", "live"),
        ),
        Scenario(
            name="signed-verifier-dies",
            description="signed mode: the signature device raises "
            "mid-run; breaker trips to the host oracle, then a probe "
            "re-closes it",
            signed=True,
            signature_plane=_flaky_signature_plane(fail_from=1, fail_until=2),
            # Past the client window width (100), so the lazy plane sees
            # multiple flushes — the failure window [1, 3) is reachable.
            reqs_per_client=120,
            tags=("device", "signed", "live"),
        ),
    ]


# The tier-1 smoke subset: one partition-with-heal, one crash-with-
# restart, one device-plane failure — the three disruption families.
SMOKE_NAMES = ("partition-minority", "crash-restart", "device-digest-dies")


def smoke_matrix() -> list:
    by_name = {s.name: s for s in matrix()}
    return [by_name[name] for name in SMOKE_NAMES]


def live_matrix() -> list:
    """The live-cluster campaign (chaos/live.py): the shared structured
    scenarios from the deterministic matrix, plus the one fault family
    only a real runtime can express (failing fsyncs)."""
    by_name = {s.name: s for s in matrix()}
    return [
        by_name["crash-restart"],
        by_name["partition-minority"],
        by_name["drop-10pct"],
        by_name["leader-isolation-epoch-change"],
        by_name["signed-verifier-dies"],
        Scenario(
            name="fsync-dies-restart",
            description="node 2's disk starts failing fsyncs at 3s; the "
            "runtime fails loudly, is crash-killed, and reboots with a "
            "healthy disk 4s later (live only)",
            storage_faults=(
                StorageFault(at_ms=3000, node=2, restart_delay_ms=4000),
            ),
            tags=("storage", "live"),
        ),
    ]


# The tier-1 live smoke: one crash+restart, one partition+heal — real
# sockets and fsyncs under a hard wall-clock budget.
LIVE_SMOKE_NAMES = ("crash-restart", "partition-minority")


def live_smoke_matrix() -> list:
    by_name = {s.name: s for s in live_matrix()}
    return [by_name[name] for name in LIVE_SMOKE_NAMES]
