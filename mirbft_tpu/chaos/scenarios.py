"""The chaos scenario matrix: one schema, two engines.

Each ``Scenario`` is declarative: factories (not instances) for manglers
and crypto planes, because both are stateful per run — the runner builds
fresh ones for every (scenario, seed) execution so campaigns are
reproducible and scenarios can repeat across seeds.

The structured fault fields — ``partitions`` (PartitionWindow),
``crashes`` (CrashPoint), ``drop_pct``, ``storage_faults``
(StorageFault), ``signed`` — are engine-agnostic: the deterministic
runner (runner.py) lowers them onto testengine manglers and simulated
crash schedules, while the live driver (live.py) lowers the *same*
scenario onto socket-level partition proxies, real crash-kills of
runtime Nodes, transport-seam message loss, and failing fsyncs.  Only
``manglers`` (the raw mangler-DSL escape hatch) is testengine-specific.

The matrix mirrors the reference's fault suite (mirbft_test.go:68-222)
and extends it with network partitions (with heal), device-plane faults
against the coalescing crypto planes, epoch-change-targeted leader
isolation, and signed-mode verifier faults."""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import pb
from ..resilience import CircuitBreaker
from ..testengine.crypto_plane import CoalescingHashPlane
from ..testengine.engine import standard_initial_network_state
from ..testengine.manglers import (
    after_time,
    from_client,
    from_source,
    is_propose,
    is_step,
    msg_type,
    partition,
    percent,
    rule,
    to_node,
    until_time,
)
from ..testengine.signing import SignaturePlane
from .faults import FlakyDigestBackend, FlakyVerifierBackend


@dataclass(frozen=True)
class CrashPoint:
    """Runner-driven crash: at ``at_ms`` simulated time, crash ``node``
    (snapshotting its durable commit log for the durability invariant)
    and reboot it from durable state ``restart_delay_ms`` later."""

    at_ms: int
    node: int
    restart_delay_ms: int


@dataclass(frozen=True)
class PartitionWindow:
    """Declarative network split: messages crossing between ``groups``
    are cut for ``from_ms <= t < until_ms``, then the network heals.
    The deterministic runner lowers this onto the partition() mangler;
    the live driver cuts the socket-level partition proxies."""

    groups: tuple  # tuple of tuples of node ids, covering all nodes
    from_ms: int
    until_ms: int


@dataclass(frozen=True)
class NodeJoin:
    """Reconfiguration under fire (mp driver only): ``node`` is a
    provisioned member of the network config that is *not* booted at
    cluster start — the running subset carries the bootstrap leader set,
    so the absent member owns no buckets.  At ``at_ms`` the supervisor
    spawns it fresh (``join_node``) against the running cluster; it must
    reach the commit frontier via checkpoint-anchored snapshot state
    transfer within ``catchup_bound_ms`` (``check_bounded_catchup``)."""

    at_ms: int
    node: int
    catchup_bound_ms: int = 60_000
    # When True the joiner is admitted by a committed pb.Reconfiguration
    # (the mp driver submits the grown config through the ordered
    # broadcast and only spawns the node once an incumbent has adopted
    # it) rather than by a static provisioned spec.
    via_reconfig: bool = False


@dataclass(frozen=True)
class NodeRemoval:
    """Reconfiguration under fire (mp driver only): at ``at_ms`` the
    node is permanently removed — SIGKILL with no restart — and the
    survivors must keep committing (quorums permitting)."""

    at_ms: int
    node: int
    # When True the survivors also commit a pb.Reconfiguration that
    # shrinks the config to exclude ``node``; the departure is a
    # membership change, not just a silent crash.
    via_reconfig: bool = False


@dataclass(frozen=True)
class ReconfigPoint:
    """A reconfiguration riding the ordered broadcast (deterministic
    engine): when request ``(client_id, req_no)`` commits, every node's
    app observes ``build()``'s ``pb.Reconfiguration`` list and reports
    it with its next checkpoint; the new config activates at the next
    stable checkpoint (commitstate's pending -> reconfigured seam).

    ``joins`` names deferred nodes the runner provisions — from
    ``provision_from``'s newest stable checkpoint whose config includes
    them — once the grown config is *active* at that member (the
    operator-side half of a node-set reconfiguration).  ``add_clients``
    are ``(client_id, total_reqs)`` pairs registered with the engine
    once the adopted config's client set carries them."""

    client_id: int
    req_no: int
    build: object  # zero-arg factory -> [pb.Reconfiguration]
    joins: tuple = ()
    provision_from: int = 0
    provision_delay_ms: int = 50
    add_clients: tuple = ()


@dataclass(frozen=True)
class StorageFault:
    """Live-only fault: from ``at_ms`` the node's WAL/reqstore fsyncs
    raise OSError, so the runtime's persist path fails loudly; the
    driver crash-kills the node and reboots it — with healthy storage —
    ``restart_delay_ms`` after the fault hit."""

    at_ms: int
    node: int
    restart_delay_ms: int


@dataclass(frozen=True)
class Adversary:
    """Engine-agnostic Byzantine attack: a compromised node (or link)
    attacking *content and ordering* rather than delivery — the malicious-
    leader model of the Mir paper's robustness evaluation.  The
    deterministic runner lowers each spec onto the adversarial mangler
    actions (``lower()``); the live driver lowers the same spec onto
    frame-rewriting socket proxies and the signed ingress gate.

    Kinds:

    * ``corrupt`` — flip ``byte_flips`` bytes of matched payloads/digests
      in flight.  ``msg_kinds=("Propose",)`` attacks client proposals
      (signed mode must reject 100%); other kinds name wire messages.
      ``victims`` restricts to deliveries into those nodes (empty = all).
      ``msg_kinds=("SnapshotChunk",)`` attacks the snapshot
      state-transfer stream instead (live/mp drivers only): chunk frames
      are bit-flipped or tail-truncated (``corrupt``) or dropped
      (``censor``), and the fetcher's digest chain must reject 100%.
    * ``equivocate`` — ``node`` (a leader) sends conflicting Preprepares
      for the same (epoch, seq) to the ``victims`` follower subset.
    * ``censor`` — ``node`` silently drops every event speaking for the
      ``victims`` client ids at its ingress (proposals, acks, forwards);
      defeated by epoch-rotation of bucket assignment.
    * ``flood`` — ``copies`` delayed echoes of matched messages spread
      over ``stale_delay_ms`` (duplication / stale-ack storms against the
      dedup path).  ``msg_kinds=("Propose",)`` storms client submissions.
    * ``forge_mac`` — tamper with matched replica-channel traffic under
      MAC-authenticated links (``Scenario.link_auth``).  The
      deterministic lowering rewrites matched wire messages (fresh,
      unsealed objects the MacSealPlane must refuse); the live lowering
      flips raw authenticator-tag bytes at the frame tail, so the frame
      stays structurally parseable and the rejection is attributable to
      the MAC check alone.  Counts touches on ``forged_macs`` (live) /
      the corrupt counters (deterministic).
    """

    kind: str  # "corrupt" | "equivocate" | "censor" | "flood" | "forge_mac"
    # The compromised node.  For corrupt/flood over wire messages it
    # scopes from_source; -1 means any source (a compromised network
    # rather than a compromised node).  Corrupting RequestAcks from more
    # than f sources exceeds Mir's threat model: ack integrity is a
    # signature property, so in-flight ack corruption models a *lying
    # acker*, and the one-vote-per-node rule rightly wedges availability
    # past f liars.
    node: int = 0
    victims: tuple = ()  # nodes (equivocate/corrupt) or client ids (censor)
    from_ms: int = 0
    until_ms: int | None = None  # None = attacks for the whole run
    rate_pct: int = 100
    byte_flips: int = 1  # corrupt
    msg_kinds: tuple = ("Propose",)  # corrupt/flood surface
    copies: int = 2  # flood echoes per matched message
    stale_delay_ms: int = 4000  # flood echo spread

    def lower(self):
        """Build the testengine mangler for this attack (fresh state per
        call; all randomness seeded via the recorder)."""
        window = []
        if self.from_ms:
            window.append(after_time(self.from_ms))
        if self.until_ms is not None:
            window.append(until_time(self.until_ms))
        # percent() burns an rng draw per candidate it sees; keep it last
        # so only events the cheap predicates matched consume randomness.
        gate = [percent(self.rate_pct)] if self.rate_pct < 100 else []
        if self.kind in ("corrupt", "forge_mac"):
            # forge_mac's deterministic lowering IS a corrupt mangler:
            # every rewrite builds a fresh, unsealed message object, which
            # is exactly what the MacSealPlane rejects at delivery.
            if self.msg_kinds == ("Propose",):
                base = [is_propose()]
            else:
                base = [msg_type(*self.msg_kinds)]
                if self.node >= 0:
                    base.append(from_source(self.node))
            if self.victims:
                base.append(to_node(*self.victims))
            return rule(*base, *window, *gate).corrupt(self.byte_flips)
        if self.kind == "equivocate":
            return rule(
                msg_type("Preprepare"), from_source(self.node), *window, *gate
            ).equivocate(self.victims)
        if self.kind == "censor":
            return rule(
                to_node(self.node), from_client(*self.victims), *window
            ).censor()
        if self.kind == "flood":
            if self.msg_kinds == ("Propose",):
                base = [is_propose()]
            else:
                base = [msg_type(*self.msg_kinds)]
                if self.node >= 0:
                    base.append(from_source(self.node))
            return rule(*base, *window, *gate).flood(
                self.copies, self.stale_delay_ms
            )
        raise ValueError(f"unknown adversary kind {self.kind!r}")


def _rotating_network_state(
    node_count: int = 4,
    client_ids: tuple = (4, 5),
    max_epoch_length: int = 40,
    checkpoint_interval: int | None = None,
):
    """Factory for a network state with a short planned epoch length, so
    graceful bucket rotation — the paper's anti-censorship defense —
    happens within a scenario run instead of after the default 10
    checkpoint windows.  ``checkpoint_interval`` additionally shrinks
    the watermark window, which is how state-transfer scenarios make a
    rebooted node fall a full certified checkpoint behind quickly."""

    def build():
        base = standard_initial_network_state(node_count, list(client_ids))
        # Construct the variant config rather than mutating the standard
        # one in place: NetworkConfig mutation outside the adoption seam
        # is banned (lint rule W20) because live trackers alias it.
        return pb.NetworkState(
            config=pb.NetworkConfig(
                nodes=list(base.config.nodes),
                f=base.config.f,
                number_of_buckets=base.config.number_of_buckets,
                checkpoint_interval=(
                    checkpoint_interval or base.config.checkpoint_interval
                ),
                max_epoch_length=max_epoch_length,
            ),
            clients=base.clients,
        )

    return build


def _grow_network_state():
    """Factory for the node-set-growth universe: 4 active members (0..3)
    of a 5-node simulated universe, short checkpoint windows so adoption
    lands early, client widths covering the whole request stream (the
    deterministic engine submits each request exactly once)."""

    def build():
        return pb.NetworkState(
            config=pb.NetworkConfig(
                nodes=[0, 1, 2, 3],
                f=1,
                number_of_buckets=4,
                checkpoint_interval=8,
                max_epoch_length=16,
            ),
            clients=[
                pb.NetworkClient(id=cid, width=48, low_watermark=0)
                for cid in (5, 6)
            ],
        )

    return build


def _five_node_reconfig():
    """The committed grow payload: the 5-node config node 4 joins under.
    Bucket count stays at 4 so in-flight bucket ownership is stable
    across the flip; only membership/f change."""
    return [
        pb.Reconfiguration(
            type=pb.NetworkConfig(
                nodes=[0, 1, 2, 3, 4],
                f=1,
                number_of_buckets=4,
                checkpoint_interval=8,
                max_epoch_length=16,
            )
        )
    ]


def _mel_reconfig(max_epoch_length: int):
    """A full-replacement NetworkConfig payload differing from the
    4-node standard config only in ``max_epoch_length`` — the benign
    knob the equivocating-configs scenario uses to build a *conflicting
    pair* without destabilizing watermarks or bucket maps mid-run."""

    def build():
        return [
            pb.Reconfiguration(
                type=pb.NetworkConfig(
                    nodes=[0, 1, 2, 3],
                    f=1,
                    number_of_buckets=4,
                    checkpoint_interval=20,
                    max_epoch_length=max_epoch_length,
                )
            )
        ]

    return build


@dataclass
class Scenario:
    name: str
    description: str = ""
    tags: tuple = ()
    node_count: int = 4
    client_count: int = 2
    reqs_per_client: int = 10
    batch_size: int = 1
    # Zero-arg factory -> list of manglers (fresh state per run).
    # Testengine-only: prefer the structured fields below, which both
    # engines understand.
    manglers: object = None
    crashes: tuple = ()  # CrashPoints, fired by the runner
    partitions: tuple = ()  # PartitionWindows (both engines)
    drop_pct: int = 0  # uniform message-loss percentage (both engines)
    storage_faults: tuple = ()  # StorageFaults (live driver only)
    joins: tuple = ()  # NodeJoins (mp driver only)
    removes: tuple = ()  # NodeRemovals (mp driver only)
    # Committed-reconfiguration triggers (deterministic engine): the
    # runner wires each onto Recorder.reconfig_on_commit, provisions
    # the joined nodes after adoption, and audits config agreement.
    reconfigs: tuple = ()  # ReconfigPoints
    # Nodes in the simulated universe that boot only after a node-set
    # reconfiguration adds them (paired with ReconfigPoint.joins).
    deferred_nodes: tuple = ()
    # Signed-request mode: clients Ed25519-sign, replicas verify at
    # ingress through a SignaturePlane (factory below, fresh per run).
    signed: bool = False
    signature_plane: object = None  # zero-arg factory (signed mode)
    # MAC-authenticated replica channels (docs/CRYPTO.md): the
    # deterministic runner installs a MacSealPlane, the live driver
    # turns on per-link transport MACs.  Opt-in so digest-layer
    # corruption scenarios keep observing their evidence where it is.
    link_auth: bool = False
    # Post-run aggregate-certificate audit: collect the run's checkpoint
    # quorum certificates, verify every genuine one and reject every
    # forged variant through the crypto/qc.py seam.
    cert_audit: bool = False
    # Byzantine attacks (Adversary specs); both engines lower them.
    adversaries: tuple = ()
    # The scenario is designed to force an epoch change; the runner
    # fails it unless some node ends beyond the first working epoch.
    expect_epoch_change: bool = False
    # Zero-arg factory -> initial NetworkState (overrides the standard
    # one; censorship scenarios shorten max_epoch_length so bucket
    # rotation lands inside the run).
    network_state: object = None
    # Zero-arg factory -> hash plane (fresh breaker/counters per run).
    hash_plane: object = None
    # Heal instants (ms) of disruptions the raw manglers inject;
    # structured faults (partitions/crashes/storage) are added
    # automatically by disruption_ends().
    heal_points_ms: tuple = ()
    recovery_bound_ms: int = 120_000
    max_steps: int = 600_000
    notes: dict = field(default_factory=dict)

    def disruption_ends(self) -> list:
        ends = list(self.heal_points_ms)
        ends.extend(w.until_ms for w in self.partitions)
        ends.extend(c.at_ms + c.restart_delay_ms for c in self.crashes)
        ends.extend(s.at_ms + s.restart_delay_ms for s in self.storage_faults)
        ends.extend(j.at_ms for j in self.joins)
        ends.extend(r.at_ms for r in self.removes)
        return ends

    def build_manglers(self) -> list:
        """Lower the structured fault fields onto testengine manglers
        (plus any raw ``manglers`` the scenario carries).  Fresh mangler
        state per call, so runs stay independent."""
        built = []
        for window in self.partitions:
            built.append(
                partition(
                    [list(group) for group in window.groups],
                    from_ms=window.from_ms,
                    until_ms=window.until_ms,
                )
            )
        if self.drop_pct:
            built.append(rule(is_step(), percent(self.drop_pct)).drop())
        for adversary in self.adversaries:
            built.append(adversary.lower())
        if self.manglers:
            built.extend(self.manglers())
        return built


def _flaky_plane(mode: str, **kwargs):
    """Factory-factory: a CoalescingHashPlane whose backend misbehaves for
    a call window, guarded by a hair-trigger breaker.

    The lazy plane coalesces a whole run into ~4 backend calls, so the
    window ``fail_from=1, fail_until=3`` with threshold/probe of 1 walks
    the breaker through its full lifecycle deterministically: call 0
    healthy, call 1 fails (trip → open), call 2 is a probe and fails
    (re-open), call 3 is a probe and succeeds (re-close)."""

    def build():
        return CoalescingHashPlane(
            digest_many=FlakyDigestBackend(mode=mode, **kwargs),
            breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
            timeout_s=0.0005 if mode == "slow" else None,
        )

    return build


def _flaky_signature_plane(**kwargs):
    """Factory-factory: a SignaturePlane whose verifier backend
    misbehaves for a call window, guarded by the same hair-trigger
    breaker as _flaky_plane so the trip → fallback → probe → re-close
    cycle is walked deterministically."""

    def build():
        return SignaturePlane(
            verifier=FlakyVerifierBackend(**kwargs),
            breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
        )

    return build


def matrix() -> list:
    """The full campaign: baseline, the reference fault suite, partitions
    with heal, crash schedules, device-plane faults, and combinations."""
    return [
        Scenario(
            name="baseline",
            description="no faults; anchors event counts for the seed",
        ),
        Scenario(
            name="jitter-30ms",
            description="30ms delivery jitter on every message",
            manglers=lambda: [rule(is_step()).jitter(30)],
        ),
        Scenario(
            name="jitter-1000ms",
            description="1000ms delivery jitter (reorders across ticks)",
            manglers=lambda: [rule(is_step()).jitter(1000)],
        ),
        Scenario(
            name="duplicate-75pct",
            description="75% of messages delivered twice (delayed echo)",
            manglers=lambda: [rule(is_step(), percent(75)).duplicate(300)],
        ),
        Scenario(
            name="drop-10pct",
            description="10% uniform message loss",
            drop_pct=10,
        ),
        Scenario(
            name="ack-loss-70pct",
            description="70% RequestAck loss from nodes 1 and 2",
            manglers=lambda: [
                rule(msg_type("RequestAck"), from_source(1, 2), percent(70))
                .drop()
            ],
        ),
        Scenario(
            name="partition-minority",
            description="node 0 isolated 2s..12s, then heals",
            partitions=(
                PartitionWindow(
                    groups=((0,), (1, 2, 3)), from_ms=2000, until_ms=12_000
                ),
            ),
        ),
        Scenario(
            name="partition-split-2-2",
            description="2-2 split (no quorum anywhere) 2s..10s, then heals",
            partitions=(
                PartitionWindow(
                    groups=((0, 1), (2, 3)), from_ms=2000, until_ms=10_000
                ),
            ),
        ),
        Scenario(
            name="partition-flapping",
            description="node 3 isolated twice: 2s..6s and 9s..13s",
            partitions=(
                PartitionWindow(
                    groups=((3,), (0, 1, 2)), from_ms=2000, until_ms=6000
                ),
                PartitionWindow(
                    groups=((3,), (0, 1, 2)), from_ms=9000, until_ms=13_000
                ),
            ),
        ),
        Scenario(
            name="crash-restart",
            description="node 1 crashes at 3s, reboots from WAL 5s later",
            crashes=(CrashPoint(at_ms=3000, node=1, restart_delay_ms=5000),),
        ),
        Scenario(
            name="crash-staggered-pair",
            description="nodes 1 and 2 crash/restart at staggered times "
            "(never below quorum simultaneously)",
            crashes=(
                CrashPoint(at_ms=3000, node=1, restart_delay_ms=5000),
                CrashPoint(at_ms=12_000, node=2, restart_delay_ms=5000),
            ),
        ),
        Scenario(
            name="device-digest-dies",
            description="digest device raises mid-run; breaker trips to "
            "host oracle, then a probe re-closes it",
            hash_plane=_flaky_plane("die", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="device-digest-short-read",
            description="digest device returns half a batch (lying "
            "readback); plane recomputes on host",
            hash_plane=_flaky_plane("short", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="device-digest-hangs",
            description="digest device exceeds its deadline for a window; "
            "timeouts trip the breaker",
            hash_plane=_flaky_plane("slow", fail_from=1, fail_until=3),
            tags=("device",),
        ),
        Scenario(
            name="partition-plus-crash",
            description="node 0 isolated 2s..10s while node 2 crashes at "
            "4s and reboots at 9s",
            partitions=(
                PartitionWindow(
                    groups=((0,), (1, 2, 3)), from_ms=2000, until_ms=10_000
                ),
            ),
            crashes=(CrashPoint(at_ms=4000, node=2, restart_delay_ms=5000),),
        ),
        Scenario(
            name="partition-plus-duplication",
            description="2-2 split 2s..8s under 50% duplication",
            partitions=(
                PartitionWindow(
                    groups=((0, 1), (2, 3)), from_ms=2000, until_ms=8000
                ),
            ),
            manglers=lambda: [rule(is_step(), percent(50)).duplicate(300)],
        ),
        Scenario(
            name="leader-isolation-epoch-change",
            description="node 0 (a leader) isolated 2s..20s under 5% loss "
            "— held far past the suspect timeout, so the survivors must "
            "change epochs and commit the suspect's in-flight sequences "
            "exactly once",
            partitions=(
                PartitionWindow(
                    groups=((0,), (1, 2, 3)), from_ms=2000, until_ms=20_000
                ),
            ),
            drop_pct=5,
            expect_epoch_change=True,
            tags=("epoch", "live"),
        ),
        # -- Byzantine adversary campaign (malicious leaders/links) -------
        Scenario(
            name="corrupt-propose-signed",
            description="60% of proposal deliveries into nodes 1 and 2 "
            "are bit-flipped in flight; signed ingress must reject every "
            "corruption while the honest copies (nodes 0 and 3 always "
            "reach weak quorum) and the fetch machinery still commit all",
            signed=True,
            reqs_per_client=12,
            # Victims are capped at f+1 nodes so every request keeps a
            # weak quorum of honest copies: the engine's clients never
            # resubmit, so a proposal corrupted at 2f+1 ingresses would be
            # indistinguishable from one never sent.
            adversaries=(
                Adversary(kind="corrupt", victims=(1, 2), rate_pct=60),
            ),
            tags=("adversary", "signed", "live"),
        ),
        Scenario(
            name="corrupt-digests-in-flight",
            description="node 1 lies in 60% of its request acks while 15% "
            "of Prepare/Commit digests from anywhere are bit-flipped for "
            "5s; ack lying stays within f sources (ack integrity is a "
            "signature property, so >f lying ackers exceeds the threat "
            "model) and quorum redundancy must absorb it all without "
            "forking",
            adversaries=(
                Adversary(
                    kind="corrupt",
                    node=1,
                    msg_kinds=("RequestAck",),
                    rate_pct=60,
                    until_ms=5000,
                ),
                Adversary(
                    kind="corrupt",
                    node=-1,
                    msg_kinds=("Prepare", "Commit"),
                    rate_pct=15,
                    until_ms=5000,
                ),
            ),
            heal_points_ms=(5000,),
            tags=("adversary",),
        ),
        Scenario(
            name="corrupt-forwarded-data",
            description="half the proposal deliveries into nodes 2 and 3 "
            "are lost, forcing data fetches — and 40% of the resulting "
            "ForwardRequests carry corrupted payloads the receiver's "
            "digest re-verification must drop and refetch",
            adversaries=(
                Adversary(
                    kind="corrupt",
                    node=-1,
                    msg_kinds=("ForwardRequest",),
                    rate_pct=40,
                ),
            ),
            manglers=lambda: [
                rule(is_propose(), to_node(2, 3), percent(50)).drop()
            ],
            tags=("adversary",),
        ),
        Scenario(
            name="equivocate-majority-suspect",
            description="leader 0 sends conflicting Preprepares to "
            "followers 1 and 2 for 3s; no digest can reach quorum, so the "
            "honest nodes must suspect the liar and change epochs — "
            "committing every sequence exactly once",
            adversaries=(
                Adversary(
                    kind="equivocate", node=0, victims=(1, 2), until_ms=3000
                ),
            ),
            expect_epoch_change=True,
            heal_points_ms=(3000,),
            tags=("adversary", "epoch"),
        ),
        Scenario(
            name="equivocate-minority-straggler",
            description="leader 0 lies only to follower 3 for 4s; the "
            "honest majority keeps committing and the victim must catch "
            "up (retransmission/state transfer) without ever committing "
            "the variant batch",
            reqs_per_client=20,
            adversaries=(
                Adversary(
                    kind="equivocate", node=0, victims=(3,), until_ms=4000
                ),
            ),
            heal_points_ms=(4000,),
            tags=("adversary", "live"),
        ),
        Scenario(
            name="censor-client-rotation",
            description="leader 0 silently drops everything client 4 "
            "submits (proposals, acks, forwards at its ingress) for 10s; "
            "short epochs force bucket rotation, which must hand the "
            "censored bucket to an honest leader within k rotations",
            adversaries=(
                Adversary(
                    kind="censor", node=0, victims=(4,), until_ms=10_000
                ),
            ),
            network_state=_rotating_network_state(max_epoch_length=40),
            heal_points_ms=(10_000,),
            notes={"censor_k": 3},
            tags=("adversary", "censor", "live"),
        ),
        Scenario(
            name="censor-both-clients",
            description="leader 0 censors both clients at once for 10s — "
            "every bucket it owns starves until rotation rescues them",
            adversaries=(
                Adversary(
                    kind="censor", node=0, victims=(4, 5), until_ms=10_000
                ),
            ),
            network_state=_rotating_network_state(max_epoch_length=40),
            heal_points_ms=(10_000,),
            notes={"censor_k": 3},
            tags=("adversary", "censor"),
        ),
        Scenario(
            name="flood-stale-acks",
            description="half of node 1's RequestAcks are echoed 3x up to "
            "8s late — stale acks for long-committed requests that the "
            "client windows must shrug off",
            adversaries=(
                Adversary(
                    kind="flood",
                    node=1,
                    msg_kinds=("RequestAck",),
                    copies=3,
                    stale_delay_ms=8000,
                    rate_pct=50,
                ),
            ),
            tags=("adversary", "flood"),
        ),
        Scenario(
            name="flood-duplicate-proposes",
            description="75% of client submissions are delivered 4x (the "
            "paper's request-duplication attack); dedup must commit "
            "exactly once with bounded store growth",
            adversaries=(
                Adversary(
                    kind="flood",
                    msg_kinds=("Propose",),
                    copies=3,
                    stale_delay_ms=2000,
                    rate_pct=75,
                ),
            ),
            tags=("adversary", "flood", "live"),
        ),
        Scenario(
            name="flood-threephase-storm",
            description="node 0's Preprepare/Prepare/Commit traffic is "
            "doubled with echoes up to 3s late; consensus dedup must "
            "hold watermarks and WAL growth bounded",
            adversaries=(
                Adversary(
                    kind="flood",
                    node=0,
                    msg_kinds=("Preprepare", "Prepare", "Commit"),
                    copies=2,
                    stale_delay_ms=3000,
                    rate_pct=50,
                ),
            ),
            tags=("adversary", "flood"),
        ),
        Scenario(
            name="equivocate-plus-flood",
            description="leader 0 equivocates to followers 1 and 2 while "
            "node 2's acks are storm-echoed — the epoch change must land "
            "despite the noise",
            adversaries=(
                Adversary(
                    kind="equivocate", node=0, victims=(1, 2), until_ms=4000
                ),
                Adversary(
                    kind="flood",
                    node=2,
                    msg_kinds=("RequestAck",),
                    copies=2,
                    stale_delay_ms=5000,
                    rate_pct=40,
                ),
            ),
            expect_epoch_change=True,
            heal_points_ms=(4000,),
            tags=("adversary", "epoch", "flood"),
        ),
        Scenario(
            name="forged-mac-storm",
            description="MAC-authenticated replica channels: a "
            "compromised network tampers with 30% of all Prepare/Commit "
            "traffic for 5s — every forged frame is unsealed and the "
            "per-link MAC check must reject 100% of them at ingress "
            "while consensus converges on the honest remainder",
            link_auth=True,
            adversaries=(
                Adversary(
                    kind="forge_mac",
                    node=-1,
                    msg_kinds=("Prepare", "Commit"),
                    rate_pct=30,
                    until_ms=5000,
                ),
            ),
            heal_points_ms=(5000,),
            tags=("adversary", "mac", "live"),
        ),
        Scenario(
            name="forged-aggregate-cert",
            description="aggregate quorum certificates: checkpoints "
            "accumulate BLS votes into one aggregate signature per "
            "certificate; after the run every genuine certificate must "
            "verify under a single aggregate check and every forged "
            "variant (mismatched statement, wrong signer set) must be "
            "rejected — the qc seam's 100%-rejection audit",
            reqs_per_client=20,
            cert_audit=True,
            network_state=_rotating_network_state(
                max_epoch_length=60, checkpoint_interval=6
            ),
            tags=("adversary", "cert", "live"),
        ),
        Scenario(
            name="signed-verifier-dies",
            description="signed mode: the signature device raises "
            "mid-run; breaker trips to the host oracle, then a probe "
            "re-closes it",
            signed=True,
            signature_plane=_flaky_signature_plane(fail_from=1, fail_until=2),
            # Past the client window width (100), so the lazy plane sees
            # multiple flushes — the failure window [1, 3) is reachable.
            reqs_per_client=120,
            tags=("device", "signed", "live"),
        ),
        # -- dynamic membership (committed reconfigurations) ---------------
        Scenario(
            name="reconfig-add-node",
            description="a committed NetworkConfig reconfiguration grows "
            "the replica set 4 -> 5 at a checkpoint boundary; node 4 is "
            "provisioned from a member's reconfigured checkpoint and "
            "commits the tail of the workload as a full member",
            node_count=5,
            client_count=2,
            reqs_per_client=40,
            batch_size=2,
            network_state=_grow_network_state(),
            deferred_nodes=(4,),
            reconfigs=(
                ReconfigPoint(
                    client_id=5,
                    req_no=2,
                    build=_five_node_reconfig,
                    joins=(4,),
                ),
            ),
            recovery_bound_ms=300_000,
            max_steps=2_000_000,
            tags=("reconfig",),
        ),
        Scenario(
            name="reconfig-crash-straddle",
            description="the 4 -> 5 grow again, with member 1 crashing "
            "around the adoption window and replaying the "
            "C(pending)+C(reconfigured) pair from its WAL",
            node_count=5,
            client_count=2,
            reqs_per_client=40,
            batch_size=2,
            network_state=_grow_network_state(),
            deferred_nodes=(4,),
            reconfigs=(
                ReconfigPoint(
                    client_id=5,
                    req_no=2,
                    build=_five_node_reconfig,
                    joins=(4,),
                ),
            ),
            crashes=(CrashPoint(at_ms=2000, node=1, restart_delay_ms=3000),),
            recovery_bound_ms=300_000,
            max_steps=2_000_000,
            tags=("reconfig",),
        ),
        Scenario(
            name="reconfig-partition-flip",
            description="a 2-2 split of the incumbents spans the config "
            "flip: the reconfiguration can only stabilize after the heal, "
            "and the joiner provisions from the post-heal checkpoint",
            node_count=5,
            client_count=2,
            reqs_per_client=40,
            batch_size=2,
            network_state=_grow_network_state(),
            deferred_nodes=(4,),
            reconfigs=(
                ReconfigPoint(
                    client_id=5,
                    req_no=2,
                    build=_five_node_reconfig,
                    joins=(4,),
                ),
            ),
            partitions=(
                PartitionWindow(
                    groups=((0, 1), (2, 3, 4)), from_ms=1000, until_ms=5000
                ),
            ),
            recovery_bound_ms=300_000,
            max_steps=2_000_000,
            tags=("reconfig",),
        ),
        Scenario(
            name="reconfig-equivocate-configs",
            description="two conflicting NetworkConfig payloads (differing "
            "max_epoch_length) commit in total order while leader 0 "
            "equivocates Preprepares to followers 1 and 2 — no pair of "
            "correct nodes may adopt divergent configs at any checkpoint, "
            "and all must converge on the final (last-committed) config",
            reqs_per_client=30,
            reconfigs=(
                ReconfigPoint(
                    client_id=4, req_no=3, build=_mel_reconfig(40)
                ),
                ReconfigPoint(
                    client_id=5, req_no=3, build=_mel_reconfig(80)
                ),
            ),
            adversaries=(
                Adversary(
                    kind="equivocate", node=0, victims=(1, 2), until_ms=3000
                ),
            ),
            expect_epoch_change=True,
            heal_points_ms=(3000,),
            recovery_bound_ms=300_000,
            max_steps=2_000_000,
            tags=("reconfig", "adversary"),
        ),
    ]


# The tier-1 smoke subset: one partition-with-heal, one crash-with-
# restart, one device-plane failure, one committed node-set
# reconfiguration — the four disruption families.
SMOKE_NAMES = (
    "partition-minority",
    "crash-restart",
    "device-digest-dies",
    "reconfig-add-node",
)


def reconfig_matrix() -> list:
    """The dynamic-membership subset of the matrix (committed
    reconfigurations under crashes/partitions/equivocation), selected by
    ``chaos --reconfig``."""
    return [s for s in matrix() if "reconfig" in s.tags]


def smoke_matrix() -> list:
    by_name = {s.name: s for s in matrix()}
    return [by_name[name] for name in SMOKE_NAMES]


def adversary_matrix() -> list:
    """The Byzantine subset of the matrix (corrupt / equivocate / censor /
    flood attacks), selected by ``chaos --adversary``."""
    return [s for s in matrix() if "adversary" in s.tags]


# The tier-1 adversary smoke: one equivocation forcing suspicion + epoch
# change, one duplication flood against the dedup path — the two attack
# families with the richest invariants, cheap enough for tier-1.
ADVERSARY_SMOKE_NAMES = (
    "equivocate-majority-suspect",
    "flood-duplicate-proposes",
)


def adversary_smoke_matrix() -> list:
    by_name = {s.name: s for s in matrix()}
    return [by_name[name] for name in ADVERSARY_SMOKE_NAMES]


def live_matrix() -> list:
    """The live-cluster campaign (chaos/live.py): the shared structured
    scenarios from the deterministic matrix, plus the one fault family
    only a real runtime can express (failing fsyncs)."""
    by_name = {s.name: s for s in matrix()}
    return [
        by_name["crash-restart"],
        by_name["partition-minority"],
        by_name["drop-10pct"],
        by_name["leader-isolation-epoch-change"],
        by_name["signed-verifier-dies"],
        Scenario(
            name="fsync-dies-restart",
            description="node 2's disk starts failing fsyncs at 3s; the "
            "runtime fails loudly, is crash-killed, and reboots with a "
            "healthy disk 4s later (live only)",
            storage_faults=(
                StorageFault(at_ms=3000, node=2, restart_delay_ms=4000),
            ),
            tags=("storage", "live"),
        ),
    ]


# The tier-1 live smoke: one crash+restart, one partition+heal — real
# sockets and fsyncs under a hard wall-clock budget.
LIVE_SMOKE_NAMES = ("crash-restart", "partition-minority")


def live_smoke_matrix() -> list:
    by_name = {s.name: s for s in live_matrix()}
    return [by_name[name] for name in LIVE_SMOKE_NAMES]


# The live adversary campaign (`chaos --live --adversary`): the shared
# structured Adversary scenarios the live driver can lower onto its
# frame-rewriting proxies and the signed ingress gate.
LIVE_ADVERSARY_NAMES = (
    "corrupt-propose-signed",
    "equivocate-minority-straggler",
    "censor-client-rotation",
    "flood-duplicate-proposes",
    "forged-mac-storm",
    "forged-aggregate-cert",
)


def transfer_corrupt_scenario() -> Scenario:
    """Live-only (the snapshot transfer lane exists on the real
    transport, not in the deterministic engine): a rebooted straggler
    must catch up by state transfer while its deterministic first donor
    (node 0 — the fetcher walks its peer list in order) corrupts every
    chunk it serves.  The digest chain must reject 100% of the
    corruption with counter evidence, and the fetch must fail over to
    an honest donor and still install a certified snapshot."""
    return Scenario(
        name="transfer-corrupt-stream",
        description=(
            "node 2 reboots far behind a fast-checkpointing cluster; "
            "every snapshot chunk its first donor sends is bit-flipped "
            "or truncated in flight — the digest chain rejects all of "
            "it and the fetch fails over to an honest donor"
        ),
        reqs_per_client=20,
        crashes=(CrashPoint(at_ms=2000, node=2, restart_delay_ms=6000),),
        adversaries=(
            Adversary(
                kind="corrupt",
                node=0,
                victims=(2,),
                msg_kinds=("SnapshotChunk",),
            ),
        ),
        network_state=_rotating_network_state(
            max_epoch_length=60, checkpoint_interval=6
        ),
        tags=("adversary", "transfer", "live"),
    )


def live_adversary_matrix() -> list:
    by_name = {s.name: s for s in matrix()}
    return [by_name[name] for name in LIVE_ADVERSARY_NAMES] + [
        transfer_corrupt_scenario()
    ]
