"""CLI entry: ``python -m mirbft_tpu.chaos [--seed N] [--seeds K] [--smoke]
[--live] [--adversary] [--cluster {threads,mp}] [--only S] [--json]``.

``--json`` replaces the human report with one JSON document per
campaign; each failed scenario carries a ``dump`` field pointing at the
flight-recorder segment flushed when its invariant fired (feed the
directory to ``python -m mirbft_tpu.obsv --postmortem``).

``--live`` runs the campaign against a real loopback TCP cluster
instead of the deterministic testengine; ``--smoke`` selects each
mode's tier-1 subset; ``--adversary`` swaps in the Byzantine matrix
(corrupting, equivocating, censoring, and flooding leaders) on either
engine.  ``--cluster`` picks the live cluster shape: ``threads``
(default, chaos/live.py — every node in this process) or ``mp``
(cluster/chaos_mp.py — one OS process per node, SIGKILL crashes,
restart-from-disk, socket-proxy partitions).

Exit status 0 iff every selected scenario passed all invariants (under
every seed of the sweep, when ``--seeds`` > 1)."""

from __future__ import annotations

import argparse
import json
import sys

from .live import run_live_campaign
from .runner import run_campaign
from .scenarios import (
    adversary_matrix,
    adversary_smoke_matrix,
    live_adversary_matrix,
    live_matrix,
    live_smoke_matrix,
    matrix,
    smoke_matrix,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mirbft_tpu.chaos",
        description="Seeded chaos campaign over the mirbft-tpu testengine "
        "(deterministic) or a real loopback TCP cluster (--live).",
    )
    parser.add_argument(
        "--seed", type=int, default=0, help="campaign base seed (default 0)"
    )
    parser.add_argument(
        "--seeds",
        type=int,
        default=1,
        metavar="N",
        help="sweep N campaigns at seeds seed..seed+N-1 (default 1)",
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="run only the tier-1 smoke subset",
    )
    parser.add_argument(
        "--live",
        action="store_true",
        help="run against a real loopback TCP cluster (real nodes, "
        "sockets, fsyncs) instead of the deterministic testengine",
    )
    parser.add_argument(
        "--adversary",
        action="store_true",
        help="run the Byzantine adversary matrix (corrupting, "
        "equivocating, censoring, and flooding leaders) instead of the "
        "crash/partition fault matrix",
    )
    parser.add_argument(
        "--cluster",
        default="threads",
        choices=("threads", "mp"),
        help="live cluster shape (--live only): threads = all nodes in "
        "this process (default); mp = one OS process per node via the "
        "cluster supervisor (true kill -9, restart-from-disk, proxied "
        "partitions)",
    )
    parser.add_argument(
        "--only",
        default=None,
        help="run only scenarios whose name contains this substring",
    )
    parser.add_argument(
        "--budget",
        type=float,
        default=90.0,
        metavar="S",
        help="per-scenario wall-clock budget in seconds (--live only, "
        "default 90)",
    )
    parser.add_argument(
        "--processor",
        default="serial",
        choices=("serial", "pool", "tpu", "tpu-pool", "pipelined", "tpu-pipelined"),
        help="action executor every live replica runs (--live only, "
        "default serial); the full fault matrix must pass under any of "
        "them",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="print one machine-readable JSON document per campaign "
        "instead of the human report; failed scenarios carry the flight "
        "recorder dump path under 'dump'",
    )
    parser.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    args = parser.parse_args(argv)

    if args.live and args.cluster == "mp":
        # The mp matrix is already the smoke-sized pair + the dedup
        # storm; process-per-node runs are too heavy for a long matrix.
        from ..cluster.chaos_mp import mp_adversary_matrix, mp_matrix

        scenarios = mp_adversary_matrix() if args.adversary else mp_matrix()
    elif args.live:
        if args.adversary:
            scenarios = live_adversary_matrix()
        else:
            scenarios = live_smoke_matrix() if args.smoke else live_matrix()
    elif args.adversary:
        scenarios = (
            adversary_smoke_matrix() if args.smoke else adversary_matrix()
        )
    else:
        scenarios = smoke_matrix() if args.smoke else matrix()
    if args.only:
        scenarios = [s for s in scenarios if args.only in s.name]
    if not scenarios:
        print("no scenarios match", file=sys.stderr)
        return 2
    if args.list:
        for scenario in scenarios:
            print(f"{scenario.name:<28} {scenario.description}")
        return 0

    if args.seeds < 1:
        print("--seeds must be >= 1", file=sys.stderr)
        return 2
    all_passed = True
    good_campaigns = 0
    for seed in range(args.seed, args.seed + args.seeds):
        if args.live and args.cluster == "mp":
            from ..cluster.chaos_mp import run_mp_campaign

            campaign = run_mp_campaign(
                scenarios,
                seed=seed,
                budget_s=max(args.budget, 180.0),
                processor=args.processor,
            )
        elif args.live:
            campaign = run_live_campaign(
                scenarios,
                seed=seed,
                budget_s=args.budget,
                processor=args.processor,
            )
        else:
            campaign = run_campaign(scenarios, seed=seed)
        if args.json:
            print(json.dumps(campaign.to_dict(), indent=2), flush=True)
        else:
            print(campaign.report(), flush=True)
        all_passed = all_passed and campaign.passed
        good_campaigns += campaign.passed
    if args.seeds > 1:
        print(
            f"sweep seeds={args.seed}..{args.seed + args.seeds - 1}: "
            f"{good_campaigns}/{args.seeds} campaigns passed"
        )
    return 0 if all_passed else 1


if __name__ == "__main__":
    sys.exit(main())
