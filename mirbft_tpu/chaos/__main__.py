"""CLI entry: ``python -m mirbft_tpu.chaos [--seed N] [--smoke] [--only S]``.

Exit status 0 iff every selected scenario passed all invariants."""

from __future__ import annotations

import argparse
import sys

from .runner import run_campaign
from .scenarios import matrix, smoke_matrix


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mirbft_tpu.chaos",
        description="Seeded chaos campaign over the mirbft-tpu testengine.",
    )
    parser.add_argument(
        "--seed", type=int, default=0, help="campaign base seed (default 0)"
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="run only the tier-1 smoke subset (3 scenarios)",
    )
    parser.add_argument(
        "--only",
        default=None,
        help="run only scenarios whose name contains this substring",
    )
    parser.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    args = parser.parse_args(argv)

    scenarios = smoke_matrix() if args.smoke else matrix()
    if args.only:
        scenarios = [s for s in scenarios if args.only in s.name]
    if not scenarios:
        print("no scenarios match", file=sys.stderr)
        return 2
    if args.list:
        for scenario in scenarios:
            print(f"{scenario.name:<28} {scenario.description}")
        return 0

    campaign = run_campaign(scenarios, seed=args.seed)
    print(campaign.report())
    return 0 if campaign.passed else 1


if __name__ == "__main__":
    sys.exit(main())
