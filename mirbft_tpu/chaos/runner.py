"""Campaign runner: executes scenarios on the testengine and audits them.

The runner drives a custom drain loop (instead of ``drain_clients``) so it
can fire runner-driven crash points at simulated instants — snapshotting
each victim's durable commit log first, which is what gives the durability
invariant its ground truth — and record when commitment progress happens,
which is what gives bounded-recovery its evidence."""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field

from .. import pb
from ..obsv import hooks
from ..obsv.metrics import Registry
from ..obsv.recorder import FlightRecorder
from ..testengine.engine import BasicRecorder
from .invariants import (
    CrashSnapshot,
    InvariantViolation,
    audit_aggregate_certs,
    check_aggregate_cert_rejected,
    check_bounded_recovery,
    check_censorship_liveness,
    check_commit_resumption,
    check_config_agreement,
    check_corruption_rejected,
    check_durable_prefix,
    check_flood_bounded,
    check_full_convergence,
    check_mac_rejected,
    check_no_fork,
    check_no_fork_under_equivocation,
    check_no_vector_divergence,
)
from .scenarios import Scenario, matrix

# The boot WAL's FEntry gracefully ends epoch 0, so every run negotiates
# epoch 1 at startup — epoch 1 *is* the quiescent baseline, and only an
# epoch beyond it is evidence of a forced change / bucket rotation.
FIRST_WORKING_EPOCH = 1

# Rotations-to-commit scale for the censorship histogram (the default
# obsv buckets are seconds — wrong scale for epoch counts).
ROTATION_BUCKETS = (0, 1, 2, 3, 4, 6, 8)


@dataclass
class ScenarioResult:
    name: str
    seed: int
    passed: bool
    events: int = 0
    sim_ms: int = 0
    commits: int = 0
    violation: str = ""
    dump: str = ""  # flight-recorder segment path on invariant failure
    counters: dict = field(default_factory=dict)

    def line(self) -> str:
        status = "ok  " if self.passed else "FAIL"
        extra = "".join(
            f" {key}={value}" for key, value in sorted(self.counters.items())
        )
        tail = f" [{self.violation}]" if self.violation else ""
        dump = f" dump={self.dump}" if self.dump else ""
        return (
            f"{status} {self.name:<28} seed={self.seed} "
            f"events={self.events} sim={self.sim_ms}ms "
            f"commits={self.commits}{extra}{tail}{dump}"
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "passed": self.passed,
            "events": self.events,
            "sim_ms": self.sim_ms,
            "commits": self.commits,
            "violation": self.violation,
            "dump": self.dump,
            "counters": dict(self.counters),
        }


@dataclass
class CampaignResult:
    seed: int
    results: list = field(default_factory=list)

    @property
    def passed(self) -> bool:
        return all(r.passed for r in self.results)

    def report(self) -> str:
        lines = [r.line() for r in self.results]
        good = sum(r.passed for r in self.results)
        lines.append(
            f"campaign seed={self.seed}: {good}/{len(self.results)} "
            f"scenarios passed"
        )
        return "\n".join(lines)

    def to_dict(self) -> dict:
        """Machine-readable campaign summary (``chaos --json``); each
        failed scenario's ``dump`` points at its postmortem segment."""
        return {
            "seed": self.seed,
            "passed": self.passed,
            "scenarios": [r.to_dict() for r in self.results],
        }


def _dump_dir() -> str:
    """Where invariant-failure flight dumps land:
    ``$MIRBFT_CHAOS_DUMP_DIR`` when set, else a per-process tempdir."""
    configured = os.environ.get("MIRBFT_CHAOS_DUMP_DIR")
    if configured:
        os.makedirs(configured, exist_ok=True)
        return configured
    root = os.path.join(
        tempfile.gettempdir(), f"mirbft-chaos-dumps-{os.getpid()}"
    )
    os.makedirs(root, exist_ok=True)
    return root


def dump_on_violation(recorder, scenario_name, seed, violation) -> str:
    """Record the failure note and flush the ring to a segment; returns
    the segment path ('' when the flush could not land)."""
    recorder.record_note(
        "invariant.violation",
        args={
            "scenario": scenario_name,
            "seed": seed,
            "violation": str(violation),
        },
    )
    if not recorder.dump_dir:
        recorder.dump_dir = os.path.join(
            _dump_dir(), f"{scenario_name}-seed{seed}"
        )
        os.makedirs(recorder.dump_dir, exist_ok=True)
    try:
        return recorder.flush("invariant-failure") or ""
    except OSError:
        return ""


def _active_config(rec, node):
    """The node's currently-active NetworkConfig, or None before its
    commit state initializes (deferred/booting nodes)."""
    machine = rec.machines.get(node)
    commit_state = getattr(machine, "commit_state", None)
    if commit_state is None or commit_state.active_state is None:
        return None
    return commit_state.active_state.config


def run_scenario(
    scenario: Scenario, seed: int = 0, registry: Registry | None = None
) -> ScenarioResult:
    """Execute one scenario under one seed and audit every invariant.
    Never raises for an invariant violation — it is reported in the
    result — but scenario-construction bugs do propagate.

    Recovery time and drop/duplicate casualties are recorded through the
    metrics registry: the one passed in, else the globally-enabled obsv
    registry, else a throwaway local one."""
    if registry is None:
        registry = hooks.metrics if hooks.enabled else Registry()
    manglers = scenario.build_manglers()
    hash_plane = scenario.hash_plane() if scenario.hash_plane else None
    signer = None
    signature_plane = None
    if scenario.signed:
        from ..testengine.signing import SignaturePlane, make_signer

        signer = make_signer()
        signature_plane = (
            scenario.signature_plane()
            if scenario.signature_plane
            else SignaturePlane()
        )
    mac_plane = None
    if scenario.link_auth:
        from ..testengine.signing import MacSealPlane

        mac_plane = MacSealPlane()
    cert_plane = None
    if scenario.cert_audit:
        from ..testengine.certs import CheckpointCertPlane

        # 2f+1 votes make a certificate; host aggregation keeps the
        # audit portable (the device path is bench.py's concern).
        f = (scenario.node_count - 1) // 3
        cert_plane = CheckpointCertPlane(quorum=2 * f + 1, use_device=False)
    rec = BasicRecorder(
        node_count=scenario.node_count,
        client_count=scenario.client_count,
        reqs_per_client=scenario.reqs_per_client,
        batch_size=scenario.batch_size,
        seed=seed,
        manglers=manglers,
        hash_plane=hash_plane,
        signer=signer,
        signature_plane=signature_plane,
        mac_plane=mac_plane,
        checkpoint_certs=cert_plane,
        network_state=(
            scenario.network_state() if scenario.network_state else None
        ),
        record=False,
        deferred_nodes=scenario.deferred_nodes,
    )

    # Committed-reconfiguration triggers: the app model reports the
    # payloads when the trigger request commits; the runner then owns the
    # operator-side half — provisioning joined nodes from a reconfigured
    # checkpoint and registering reconfiguration-added clients once the
    # new config is active somewhere.
    for point in scenario.reconfigs:
        rec.reconfig_on_commit[(point.client_id, point.req_no)] = point.build()
    joins_pending = [
        (node, point) for point in scenario.reconfigs for node in point.joins
    ]
    clients_pending = [
        (cid, total)
        for point in scenario.reconfigs
        for cid, total in point.add_clients
    ]

    def service_reconfigs() -> None:
        for node, point in list(joins_pending):
            if rec.node_states[point.provision_from].crashed:
                continue
            config = _active_config(rec, point.provision_from)
            if config is None or node not in config.nodes:
                continue
            seq = None
            checkpoints = rec.node_states[point.provision_from].checkpoints
            for cp_seq, (_v, state, _snap) in checkpoints.items():
                if node in state.config.nodes and (
                    seq is None or cp_seq > seq
                ):
                    seq = cp_seq
            if seq is None:
                continue
            rec.provision_node(
                node, point.provision_from, seq, point.provision_delay_ms
            )
            joins_pending.remove((node, point))
        for cid, total in list(clients_pending):
            for member in range(rec.node_count):
                if rec.node_states[member].crashed:
                    continue
                config_state = _active_config(rec, member)
                if config_state is None:
                    continue
                clients = rec.machines[member].commit_state.active_state.clients
                if any(c.id == cid for c in clients):
                    rec.add_client(cid, total)
                    clients_pending.remove((cid, total))
                    break

    pending = sorted(scenario.crashes, key=lambda c: c.at_ms)
    snapshots: list = []
    commit_times: list = []
    last_total = sum(rec._committed_counts.values())
    result = ScenarioResult(name=scenario.name, seed=seed, passed=False)

    # Flight recorder: reuse the globally-wired one so the dump carries
    # the engine's milestones; otherwise run a scenario-local ring (and,
    # when hooks are live, lend it to them for the scenario's duration)
    # so a violation still leaves black-box evidence behind.
    recorder = hooks.recorder if hooks.enabled else None
    own_recorder = recorder is None
    if own_recorder:
        recorder = FlightRecorder(f"chaos-{scenario.name}")
        if hooks.enabled:
            hooks.recorder = recorder
    recorder.record_note(
        "scenario.start", args={"scenario": scenario.name, "seed": seed}
    )

    censor_manglers = [m for m in manglers if hasattr(m, "censored_pairs")]
    # (client_id, req_no) -> epoch rotations (relative to the first
    # working epoch) observed when the censored request first committed
    # anywhere; the censorship-liveness invariant's evidence.
    commit_rotations: dict = {}

    def current_rotation() -> int:
        epochs = [
            rec.machines[n].epoch_tracker.current_epoch.number
            for n in range(rec.node_count)
            if not rec.node_states[n].crashed
            and rec.machines[n].epoch_tracker.current_epoch is not None
        ]
        return max(0, max(epochs, default=0) - FIRST_WORKING_EPOCH)

    def track_censored_commits() -> None:
        rotation = None
        for mangler in censor_manglers:
            for pair in mangler.censored_pairs:
                if pair in commit_rotations:
                    continue
                client = rec.clients.get(pair[0])
                if client is None or pair[1] not in client.committed_anywhere:
                    continue
                if rotation is None:
                    rotation = current_rotation()
                commit_rotations[pair] = rotation

    def fire_due_crashes() -> None:
        while pending and rec.now >= pending[0].at_ms:
            point = pending.pop(0)
            state = rec.node_states[point.node]
            snapshots.append(
                CrashSnapshot(
                    node=point.node,
                    at_ms=rec.now,
                    committed=list(state.committed_reqs),
                )
            )
            rec.crash(point.node)
            rec.schedule_restart(point.node, point.restart_delay_ms)

    try:
        check = True
        for _ in range(scenario.max_steps):
            fire_due_crashes()
            if joins_pending or clients_pending:
                service_reconfigs()
            if check or rec._progress:
                check = False
                # fully_committed ignores crashed nodes; a scenario only
                # completes once every scheduled crash has fired, every
                # reconfiguration-joined node is provisioned and every
                # node is back up and caught up.
                if (
                    not pending
                    and not joins_pending
                    and not clients_pending
                    and rec.fully_committed()
                    and not any(
                        rec.node_states[n].crashed
                        for n in range(rec.node_count)
                    )
                ):
                    break
            if not rec.step():
                raise InvariantViolation(
                    f"event queue drained before convergence "
                    f"({rec.event_count} events)"
                )
            total = sum(rec._committed_counts.values())
            if total > last_total:
                last_total = total
                commit_times.append(rec.now)
                if censor_manglers:
                    track_censored_commits()
        else:
            raise InvariantViolation(
                f"no convergence after {scenario.max_steps} steps "
                f"({rec.event_count} events, t={rec.now}ms)"
            )

        check_no_fork(rec)
        check_durable_prefix(rec, snapshots)
        check_full_convergence(rec)
        check_no_vector_divergence(rec)
        ends = scenario.disruption_ends()
        # Recovery time flows through the metrics registry so the same
        # number shows up in chaos reports, status snapshots, and tests:
        # the gauge IS the value the bounded-recovery invariant audits.
        gauge = registry.gauge(
            "mirbft_chaos_recovery_ms", scenario=scenario.name
        )
        gauge.set(rec.now - (max(ends) if ends else 0))
        result.counters["recovery_ms"] = gauge.value
        check_bounded_recovery(
            completion_ms=(max(ends) if ends else 0) + gauge.value,
            last_disruption_end_ms=max(ends) if ends else 0,
            bound_ms=scenario.recovery_bound_ms,
        )
        if ends:
            check_commit_resumption(
                commit_times, max(ends), scenario.recovery_bound_ms
            )
        if scenario.expect_epoch_change:
            epochs = [
                rec.machines[n].epoch_tracker.current_epoch.number
                for n in range(rec.node_count)
            ]
            result.counters["epoch"] = max(epochs)
            # Every run negotiates FIRST_WORKING_EPOCH at boot (the seed
            # WAL's FEntry ends epoch 0), so reaching it is not evidence
            # of a change — the cluster must have moved *beyond* it.
            if max(epochs) <= FIRST_WORKING_EPOCH:
                raise InvariantViolation(
                    "scenario expected an epoch change but every node is "
                    f"still in the boot epoch (epochs {epochs})"
                )
        if scenario.reconfigs:
            adoptions = 0
            checkpoint_configs: dict = {}
            final_configs: dict = {}
            for node in range(rec.node_count):
                machine = rec.machines[node]
                adoptions += getattr(machine, "reconfigs_adopted", 0)
                checkpoint_configs[node] = {
                    seq: pb.encode(state.config)
                    for seq, (_v, state, _snap) in rec.node_states[
                        node
                    ].checkpoints.items()
                }
                config = _active_config(rec, node)
                if (
                    config is not None
                    and not rec.node_states[node].crashed
                    and not getattr(machine, "retired", False)
                ):
                    final_configs[node] = pb.encode(config)
            evidence = check_config_agreement(
                checkpoint_configs, final_configs, adoptions
            )
            result.counters["reconfig_adoptions"] = adoptions
            result.counters["config_checkpoints"] = evidence[
                "checkpoints_compared"
            ]
        _audit_adversaries(
            scenario, rec, manglers, commit_rotations, registry, result
        )
        result.passed = True
    except InvariantViolation as violation:
        result.violation = str(violation)
        result.dump = dump_on_violation(
            recorder, scenario.name, seed, violation
        )
    finally:
        if own_recorder and hooks.recorder is recorder:
            hooks.recorder = None

    result.events = rec.event_count
    result.sim_ms = rec.now
    result.commits = last_total
    dropped = duplicated = 0
    for mangler in manglers:
        if hasattr(mangler, "dropped"):
            dropped += mangler.dropped
            result.counters["partition_drops"] = result.counters.get(
                "partition_drops", 0
            ) + mangler.dropped
        if getattr(mangler, "duplicated", 0):
            duplicated += mangler.duplicated
            result.counters["duplicates"] = result.counters.get(
                "duplicates", 0
            ) + mangler.duplicated
    if dropped:
        registry.counter(
            "mirbft_chaos_dropped_total", scenario=scenario.name
        ).inc(dropped)
    if duplicated:
        registry.counter(
            "mirbft_chaos_duplicated_total", scenario=scenario.name
        ).inc(duplicated)
    if snapshots:
        result.counters["crashes"] = len(snapshots)
    if hash_plane is not None:
        result.counters["device_errors"] = hash_plane.device_errors
        result.counters["device_timeouts"] = hash_plane.device_timeouts
        result.counters["fallback_digests"] = hash_plane.fallback_digests
        result.counters["breaker"] = hash_plane.breaker.state
        result.counters["breaker_trips"] = hash_plane.breaker.trips
    if signature_plane is not None:
        result.counters["sig_device_errors"] = signature_plane.device_errors
        result.counters["sig_fallbacks"] = signature_plane.fallback_verifies
        result.counters["sig_breaker"] = signature_plane.breaker.state
    return result


def _audit_adversaries(
    scenario, rec, manglers, commit_rotations, registry, result
) -> None:
    """Run the Byzantine invariants for whichever adversarial manglers the
    scenario carried (attribute-sniffed, so raw-DSL scenarios are audited
    identically to structured Adversary specs).  Raises
    InvariantViolation; also folds attack evidence into the result
    counters and the obsv registry."""
    corrupted = sum(getattr(m, "corrupted", 0) for m in manglers)
    corrupted_proposes = sum(
        getattr(m, "corrupted_proposes", 0) for m in manglers
    )
    flooded = sum(getattr(m, "flooded", 0) for m in manglers)
    censored = sum(getattr(m, "censored", 0) for m in manglers)
    variants: dict = {}
    for m in manglers:
        variants.update(getattr(m, "variants", {}))
    censored_pairs: set = set()
    for m in manglers:
        censored_pairs |= getattr(m, "censored_pairs", set())

    if corrupted:
        result.counters["corrupted"] = corrupted
    if scenario.signed and corrupted_proposes:
        result.counters["rejections"] = rec.byzantine_rejections
        check_corruption_rejected(rec.byzantine_rejections, corrupted_proposes)
    if variants:
        result.counters["equivocated"] = len(variants)
        check_no_fork_under_equivocation(
            rec, variants, expect_suspicion=scenario.expect_epoch_change
        )
    if any(hasattr(m, "censored_pairs") for m in manglers):
        result.counters["censored"] = censored
        k = scenario.notes.get("censor_k", 3)
        check_censorship_liveness(rec, censored_pairs, commit_rotations, k)
        rotations = list(commit_rotations.values())
        result.counters["rotations_max"] = max(rotations, default=0)
        histogram = registry.histogram(
            "mirbft_censored_commit_epochs",
            buckets=ROTATION_BUCKETS,
            scenario=scenario.name,
        )
        for rotation in rotations:
            histogram.observe(rotation)
    if any(hasattr(m, "flooded") for m in manglers):
        result.counters["flooded"] = flooded
        check_flood_bounded(rec, flooded)
    if scenario.link_auth and rec.mac_plane is not None:
        # forge_mac lowers to corrupt manglers over replica wire traffic;
        # the rewrites that were NOT proposal deliveries are the forged
        # replica messages the MAC layer is obligated to reject.
        forged = corrupted - corrupted_proposes
        result.counters["mac_rejections"] = rec.mac_plane.rejections
        check_mac_rejected(rec.mac_plane.rejections, forged, exact=True)
    if scenario.cert_audit and rec.checkpoint_certs is not None:
        certs = rec.checkpoint_certs.certificates()
        genuine_ok, genuine_total, forged_rejected, forged_total = (
            audit_aggregate_certs(certs)
        )
        result.counters["certs"] = genuine_total
        result.counters["cert_forgeries_rejected"] = forged_rejected
        check_aggregate_cert_rejected(
            genuine_ok, genuine_total, forged_rejected, forged_total
        )


def run_campaign(
    scenarios: list | None = None, seed: int = 0
) -> CampaignResult:
    """Run a scenario list (default: the full matrix) under derived
    per-scenario seeds; reproducible from ``seed`` alone."""
    if scenarios is None:
        scenarios = matrix()
    campaign = CampaignResult(seed=seed)
    for index, scenario in enumerate(scenarios):
        campaign.results.append(run_scenario(scenario, seed=seed + index))
    return campaign
