"""Live-cluster chaos: the fault campaign against the real TCP runtime.

The deterministic runner (runner.py) executes scenarios on the simulated
testengine; this driver lowers the *same* structured Scenario schema onto
a real cluster: N ``runtime.Node`` instances over ``TcpTransport`` on
loopback, with real serializer/consumer threads, real WAL/reqstore files,
and real fsyncs.  Faults become what they are in production:

- ``PartitionWindow``  -> socket-level partition proxies, one per directed
  transport edge; cutting an edge closes its listener so the dialing
  sender thread walks its reconnect backoff, healing re-binds the port.
- ``CrashPoint``       -> crash-kill the replica (no final fsync) and
  reboot it from its on-disk WAL/reqstore via ``Node.restart``.
- ``StorageFault``     -> the WAL/reqstore fsync seams start raising
  OSError; the runtime fails loudly, the driver crash-kills it, and the
  reboot gets healthy storage.
- ``drop_pct``         -> a seeded ``TransportFault`` dropping frames at
  the send seam (surfaced via the transport's ``dropped_fault`` counter).
- ``signed``           -> clients Ed25519-sign, the driver verifies at
  ingress through the scenario's SignaturePlane (flaky backends walk the
  breaker exactly as under the deterministic engine), and one forged
  request must be stopped cold.
- ``Adversary``        -> Byzantine attacks on content and ordering.
  Wire attacks (equivocate / censor / corrupt / flood of peer messages)
  become frame-rewriting ``AdversaryProxy`` edges that parse the
  transport's length-prefixed frames and rewrite, drop, or multiply
  them; proposal attacks (corrupt / censor / flood of client
  submissions) are driven at the client seam, with signed-mode
  corruption gated through the ingress SignaturePlane exactly as the
  engine's authentication filter would.

After convergence the same invariant checkers audit the run — no fork,
durable prefix across every crash-restart, bounded recovery — plus the
liveness invariant: commits *resume* within the bound after the last
heal/restart instant.  Epoch-change scenarios are additionally asserted
through the obsv ``epoch.active`` milestone counter, so the run proves
the change happened through the same telemetry operators would watch.

Scenario fault instants are authored in simulated ms against the
testengine's 500ms tick; the driver re-times them against its real tick
period (``scale_s``), so "isolated past the suspect timeout" means the
same thing under both engines.
"""

from __future__ import annotations

import os
import random
import shutil
import socket
import tempfile
import threading
import time
from types import SimpleNamespace

from .. import pb, wire
from ..obsv import hooks
from ..obsv.metrics import Registry
from ..runtime import (
    Config,
    FileRequestStore,
    FileWal,
    Node,
    build_processor,
)
from ..runtime.node import NodeStopped, standard_initial_network_state
from ..runtime.reconfig import checkpoint_network_state
from ..runtime.transfer import _KIND_CHUNK, TransferEngine
from ..runtime.transport import (
    _HELLO_SRC,
    _LEN,
    _XFER_SRC,
    TcpTransport,
    TransportFault,
)
from ..crypto.mac import TAG_LEN as _MAC_TAG_LEN
from ..testengine.manglers import _flip_bytes, _variant_digest
from .invariants import (
    CrashSnapshot,
    InvariantViolation,
    audit_aggregate_certs,
    check_aggregate_cert_rejected,
    check_bounded_recovery,
    check_censorship_liveness,
    check_commit_resumption,
    check_corruption_rejected,
    check_durable_prefix,
    check_mac_rejected,
    check_no_fork,
    check_no_fork_under_equivocation,
    check_transfer_corruption_rejected,
)
from ..obsv.recorder import FlightRecorder
from .runner import (
    FIRST_WORKING_EPOCH,
    ROTATION_BUCKETS,
    CampaignResult,
    ScenarioResult,
    dump_on_violation,
)
from .scenarios import Scenario, live_matrix

# The deterministic testengine ticks every 500 simulated ms; scenario
# fault instants are authored on that clock.
SIM_TICK_MS = 500

# Wall-clock floor for the scaled recovery bound: scheduler and fsync
# jitter on a loaded CI host must not fail a scenario whose scaled bound
# would otherwise be a couple of seconds.
MIN_RECOVERY_BOUND_MS = 15_000


def _shutdown_close(sock) -> None:
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class DropFault(TransportFault):
    """Uniform seeded frame loss at the transport send seam — the live
    lowering of ``Scenario.drop_pct``.  One instance is shared by every
    node's transport (matching the deterministic engine's single drop
    mangler); the RNG is locked because each transport calls ``on_send``
    from its own serializer/consumer threads."""

    def __init__(self, drop_pct: int, seed: int):
        self.drop_pct = drop_pct
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def on_send(self, peer_id: int, frame: bytes) -> bool:
        if self.drop_pct <= 0:
            return True
        with self._lock:
            return self._rng.random() * 100.0 >= self.drop_pct


class PartitionProxy:
    """A directed socket-level forwarder for one transport edge.

    Node A is told peer B lives at this proxy's address; each accepted
    connection dials the real upstream and two pump threads shuttle
    bytes.  While cut, the listener is *closed*: the dialing side's
    sender thread sees ECONNREFUSED and walks its reconnect backoff —
    exactly what a firewalled peer produces.  Healing re-binds the same
    port, so addresses registered via ``transport.connect`` stay valid
    across any number of cut/heal cycles and node restarts."""

    def __init__(self, upstream: tuple):
        self.upstream = tuple(upstream)
        self.cut_count = 0
        self._lock = threading.Lock()
        self._cut = False
        self._closed = False
        self._pipes: set = set()
        self._threads: list = []
        self._server = None
        self._accept_thread = None
        self._open_listener(("127.0.0.1", 0))
        self.address = self._server.getsockname()

    def _open_listener(self, address) -> None:
        server = socket.create_server(address)
        thread = threading.Thread(
            target=self._accept_loop,
            args=(server,),
            name="chaos-proxy-accept",
            daemon=True,
        )
        self._server = server
        self._accept_thread = thread
        thread.start()

    def set_cut(self, cut: bool) -> None:
        with self._lock:
            if self._closed or cut == self._cut:
                return
            self._cut = cut
            pipes = list(self._pipes) if cut else []
        if cut:
            self.cut_count += 1
            self._close_listener()
            for pipe in pipes:
                _shutdown_close(pipe)
        else:
            # SO_REUSEADDR (create_server default) makes the same-port
            # re-bind immediate; retry briefly for scheduler races.
            deadline = time.monotonic() + 10
            while True:
                try:
                    self._open_listener(self.address)
                    return
                except OSError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.02)

    def _close_listener(self) -> None:
        server, thread = self._server, self._accept_thread
        if server is None:
            return
        try:
            server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        server.close()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)

    def _accept_loop(self, server) -> None:
        while True:
            try:
                conn, _addr = server.accept()
            except OSError:
                return  # listener closed (cut or shutdown)
            with self._lock:
                stale = self._closed or self._cut or self._server is not server
            if stale:
                _shutdown_close(conn)
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=2.0)
            except OSError:
                _shutdown_close(conn)
                continue
            with self._lock:
                if self._closed or self._cut or self._server is not server:
                    _shutdown_close(conn)
                    _shutdown_close(up)
                    continue
                self._pipes.add(conn)
                self._pipes.add(up)
                pumps = [
                    threading.Thread(
                        target=self._pump,
                        args=(conn, up),
                        name="chaos-proxy-pump",
                        daemon=True,
                    ),
                    threading.Thread(
                        target=self._pump,
                        args=(up, conn),
                        name="chaos-proxy-pump",
                        daemon=True,
                    ),
                ]
                self._threads.extend(pumps)
            for pump in pumps:
                pump.start()

    def _pump(self, src, dst) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            with self._lock:
                self._pipes.discard(src)
                self._pipes.discard(dst)
            _shutdown_close(src)
            _shutdown_close(dst)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pipes = list(self._pipes)
            threads = list(self._threads)
        self._close_listener()
        for pipe in pipes:
            _shutdown_close(pipe)
        for thread in threads:
            thread.join(timeout=5)


class AdversaryProxy(PartitionProxy):
    """A frame-rewriting PartitionProxy: the live lowering of the
    adversary DSL's wire attacks.  The forward pump (dialer -> upstream)
    reassembles the transport's ``[u32 len][varint source][pb.Msg]``
    frames and hands each decoded message to ``mangle(source, msg)``,
    which returns ``None`` (pass through unchanged) or a replacement
    list: ``[]`` censors the frame, a rewritten message corrupts or
    equivocates it, and extra copies flood the receiver.  Snapshot
    state-transfer frames (the reserved ``_XFER_SRC`` lane) are opaque
    bytes, not pb.Msg; they go to ``mangle_transfer(body)`` with the
    same None / [] / replacement-list contract, so an adversary can
    corrupt, truncate, or censor a transfer stream in flight.
    Clock-sync hellos and client-proposal frames (the other reserved
    source ids) always pass untouched, as does the reverse pump — real
    peer links are one-way, so only the forward byte stream carries
    frames.

    ``mangle_raw(source, payload)`` is the byte-level seam for attacks
    below the message layer — MAC-tag forgery against link-authenticated
    frames, which must stay structurally parseable so the rejection is
    attributable to the MAC check alone.  It sees node-lane frames only
    (reserved source ids pass) and returns a replacement payload or None;
    when it rewrites a frame, the message-level manglers are skipped (a
    forged frame never reaches the decoder anyway)."""

    def __init__(
        self, upstream: tuple, mangle, mangle_transfer=None, mangle_raw=None
    ):
        self.mangle = mangle
        self.mangle_transfer = mangle_transfer
        self.mangle_raw = mangle_raw
        super().__init__(upstream)

    def _pump(self, src, dst) -> None:
        try:
            forward = dst.getpeername() == self.upstream
        except OSError:
            forward = False
        if not forward or (
            self.mangle is None
            and self.mangle_transfer is None
            and self.mangle_raw is None
        ):
            return super()._pump(src, dst)
        buf = bytearray()
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                buf += data
                out = bytearray()
                while len(buf) >= _LEN.size:
                    (length,) = _LEN.unpack(buf[: _LEN.size])
                    if len(buf) < _LEN.size + length:
                        break
                    payload = bytes(buf[_LEN.size : _LEN.size + length])
                    del buf[: _LEN.size + length]
                    out += self._rewrite(payload)
                if out:
                    dst.sendall(bytes(out))
        except OSError:
            pass
        finally:
            with self._lock:
                self._pipes.discard(src)
                self._pipes.discard(dst)
            _shutdown_close(src)
            _shutdown_close(dst)

    def _rewrite(self, payload: bytes) -> bytes:
        original = _LEN.pack(len(payload)) + payload
        try:
            source, offset = wire.decode_varint(payload, 0)
            if source == _XFER_SRC:
                return self._rewrite_transfer(payload, offset, original)
            if source >= _HELLO_SRC:
                return original  # hello / client-proposal frame
            if self.mangle_raw is not None:
                twisted = self.mangle_raw(source, payload)
                if twisted is not None:
                    return _LEN.pack(len(twisted)) + twisted
            msg = pb.decode(pb.Msg, payload[offset:])
        except ValueError:
            return original  # not ours to judge: the receiver drops it
        if self.mangle is None:
            return original
        replacement = self.mangle(source, msg)
        if replacement is None:
            return original
        prefix = payload[:offset]
        out = bytearray()
        for new_msg in replacement:
            body = prefix + pb.encode(new_msg)
            out += _LEN.pack(len(body)) + body
        return bytes(out)

    def _rewrite_transfer(self, payload, offset, original):
        """Hand a state-transfer frame body (sender varint preserved, so
        the fetcher's donor check still attributes it) to the transfer
        mangler."""
        if self.mangle_transfer is None:
            return original
        _sender, body_start = wire.decode_varint(payload, offset)
        replacement = self.mangle_transfer(payload[body_start:])
        if replacement is None:
            return original
        prefix = payload[:body_start]
        out = bytearray()
        for new_body in replacement:
            framed = prefix + new_body
            out += _LEN.pack(len(framed)) + framed
        return bytes(out)


# DurableChainLog moved to mirbft_tpu/app/journal.py when the real
# application layer landed (it is the app's durable journal, not a chaos
# artifact); re-exported here so existing imports keep working.
from ..app.journal import DurableChainLog  # noqa: E402,F401


class _TransportDuct:
    """TransferEngine's send seam over the real transport's reserved
    ``_XFER_SRC`` lane (so transfer frames ride the same proxied TCP
    links — and the same partitions and adversaries — as consensus)."""

    def __init__(self, transport):
        self.transport = transport

    def send(self, dest: int, body: bytes) -> None:
        self.transport.send_transfer(dest, body)


class LiveReplica:
    """One real node: serializer (inside Node), consumer loop thread,
    TCP transport wired through the cluster's partition proxies, and
    on-disk WAL/reqstore/app-log under the cluster's scratch root."""

    def __init__(self, cluster, node_id: int, initial_state=None, port=0):
        self.cluster = cluster
        self.node_id = node_id
        self.dir = os.path.join(cluster.root, f"node{node_id}")
        os.makedirs(self.dir, exist_ok=True)
        self.app_log = DurableChainLog(
            os.path.join(self.dir, "app.log"),
            node_id,
            on_commit=cluster._on_commit,
        )
        self.wal = FileWal(os.path.join(self.dir, "wal"))
        self.reqstore = FileRequestStore(os.path.join(self.dir, "reqs"))
        config = Config(
            id=node_id,
            batch_size=cluster.scenario.batch_size,
            processor=cluster.processor,
            link_auth=bool(cluster.auth_secret),
            auth_secret=cluster.auth_secret,
        )
        self.config = config
        if initial_state is not None:
            self.node = Node.start_new(config, initial_state)
        else:
            self.node = Node.restart(config, self.wal, self.reqstore)
        self.transport = self._bind(port)
        self.port = self.transport.address[1]
        if cluster.drop_fault is not None:
            self.transport.fault = cluster.drop_fault
        self.transport.serve(self.node)
        self.processor = build_processor(
            self.node,
            self.transport.link(),
            self.app_log,
            self.wal,
            self.reqstore,
        )
        # seq_no -> (value, pb.NetworkState): local view of this node's
        # own stable checkpoints (snapshot material lives in the engine).
        self.checkpoints: dict = {}
        # Pipelined executors hand results to the node internally; the
        # checkpoint capture below must route through their seam.
        if hasattr(self.processor, "on_results"):
            self.processor.on_results = self._capture_checkpoints
        # Real snapshot state transfer over the transport's reserved
        # lane; staged under the node dir, so a crash mid-transfer
        # resumes from the verified staged blob after restart.
        self.engine = TransferEngine(
            node_id,
            _TransportDuct(self.transport),
            staging_dir=self.dir,
            peers=[
                p
                for p in range(cluster.scenario.node_count)
                if p != node_id
            ],
            limits=config,
            install=self._install_snapshot,
            complete=self.node.state_transfer_complete,
            failed=self.node.state_transfer_failed,
            chunk_timeout_s=max(cluster.tick_seconds * 10, 0.5),
        )
        self.transport.set_transfer_sink(self.engine.on_frame)
        self.failed = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._consume,
            name=f"live-consumer-{node_id}",
            daemon=True,
        )

    def _bind(self, port: int) -> TcpTransport:
        """Bind the transport; a restart re-binds the node's original
        port (retrying through TIME_WAIT) so the partition proxies'
        upstream addresses stay valid across the reboot."""
        link_auth = None
        if self.config.link_auth:
            from ..crypto.mac import LinkAuthenticator

            link_auth = LinkAuthenticator(
                self.node_id, self.config.auth_secret
            )
        deadline = time.monotonic() + 10
        while True:
            try:
                return TcpTransport(
                    self.node_id,
                    port=port,
                    backoff_base=0.02,
                    backoff_cap=0.25,
                    dial_timeout=1.0,
                    link_auth=link_auth,
                )
            except OSError:
                if port == 0 or time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)

    def wire(self) -> None:
        for peer_id in range(self.cluster.scenario.node_count):
            if peer_id != self.node_id:
                proxy = self.cluster.proxies[(self.node_id, peer_id)]
                self.transport.connect(peer_id, proxy.address)

    def start_consumer(self) -> None:
        self._thread.start()

    def arm_storage_fault(self) -> None:
        def fail() -> None:
            raise OSError("injected fsync failure (chaos StorageFault)")

        self.wal.fault_hook = fail
        self.reqstore.fault_hook = fail

    def _capture_checkpoints(self, results) -> None:
        for cr in results.checkpoints:
            network_state = checkpoint_network_state(cr)
            self.checkpoints[cr.checkpoint.seq_no] = (cr.value, network_state)
            requests: list = []

            def _collect(ack, _data=None):
                # FileRequestStore.uncommitted hands only the ack; the
                # payload is a separate read.
                data = self.reqstore.get(ack)
                if data is not None:
                    requests.append((ack, data))

            self.reqstore.uncommitted(_collect)
            self.engine.note_checkpoint(
                cr.checkpoint.seq_no,
                cr.value,
                network_state,
                self.app_log.chain,
                requests,
            )

    def _install_snapshot(self, snap):
        """TransferEngine install callback: adopt the app chain (an
        fsynced adopt record) and the donor's uncommitted-request slice,
        then let the node persist the checkpoint CEntry."""
        self.app_log.adopt(snap.value, snap.seq_no)
        for ack, data in snap.requests:
            self.reqstore.store(ack, data)
        self.reqstore.sync()
        return snap.network_state

    def _consume(self) -> None:
        tick_seconds = self.cluster.tick_seconds
        last_tick = time.monotonic()
        try:
            while not self._stop.is_set():
                actions = self.node.ready(timeout=0.01)
                if actions is not None:
                    results = self.processor.process(actions)
                    self._capture_checkpoints(results)
                    if results.digests or results.checkpoints:
                        self.node.add_results(results)
                now = time.monotonic()
                if now - last_tick >= tick_seconds:
                    last_tick = now
                    self.node.tick()
                if actions is not None and actions.state_transfer is not None:
                    self.engine.begin(actions.state_transfer)
                self.engine.poll()
        except NodeStopped:
            pass
        except Exception as err:  # noqa: BLE001 — injected faults land here
            self.failed = err

    def snapshot(self, at_ms: int) -> CrashSnapshot:
        return CrashSnapshot(
            node=self.node_id, at_ms=at_ms, committed=list(self.app_log.commits)
        )

    def kill(self, graceful: bool = False) -> None:
        """Tear the replica down.  ``graceful=False`` models kill -9 as
        closely as an in-process harness can: storage handles close
        without their shutdown fsync, so only what the runtime already
        synced is durable."""
        self._stop.set()
        closer = getattr(self.processor, "close", None)
        if closer is not None and not graceful:
            # Crash-kill: park the pipeline *before* joining the consumer
            # — a consumer blocked in a backpressure put must be released,
            # and in-flight batches are abandoned like any other un-synced
            # work under kill -9.
            try:
                closer(wait=False)
            except TypeError:
                closer()  # PoolProcessor.close takes no args
        if self._thread.ident is not None:
            self._thread.join(timeout=10)
        if closer is not None and graceful:
            # Clean shutdown: drain in-flight batches (commits land, the
            # WAL/reqstore group syncers flush) before storage closes.
            closer()
        self.transport.close(0)
        self.node.stop()
        if graceful:
            self.wal.close()
            self.reqstore.close()
            self.app_log.close()
        else:
            self.wal.crash()
            self.reqstore.crash()
            self.app_log.crash()


class _LiveAdversary:
    """Wall-clock lowering of one structured ``Adversary`` spec: the
    attack window re-timed against the cluster's tick period, a seeded
    RNG behind a lock (proxy pump threads and the proposer thread fire
    concurrently), and the same evidence counters the deterministic
    manglers expose — so the invariant checkers audit both engines on
    identical inputs."""

    def __init__(self, spec, cluster, seed: int):
        self.spec = spec
        self.cluster = cluster
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.corrupted = 0
        self.corrupted_proposes = 0
        self.rejections = 0
        self.flooded = 0
        self.censored = 0
        self.corrupted_transfer = 0
        self.censored_transfer = 0
        self.forged_macs = 0
        self.censored_pairs: set = set()
        self.variants: dict = {}
        self.from_s = cluster.scale_s(spec.from_ms)
        self.until_s = (
            None if spec.until_ms is None else cluster.scale_s(spec.until_ms)
        )

    def active(self) -> bool:
        start = self.cluster._start
        if start is None:
            return False
        now_s = time.monotonic() - start
        if now_s < self.from_s:
            return False
        return self.until_s is None or now_s < self.until_s

    def fires(self) -> bool:
        if self.spec.rate_pct >= 100:
            return True
        with self._lock:
            return self._rng.random() * 100.0 < self.spec.rate_pct

    def flip(self, data: bytes) -> bytes:
        with self._lock:
            return _flip_bytes(data, self._rng, self.spec.byte_flips)

    def wire_kind_matches(self, msg: pb.Msg) -> bool:
        return type(msg.type).__name__ in self.spec.msg_kinds

    def attacks_transfer(self) -> bool:
        """Is this spec a snapshot state-transfer stream attack?  The
        DSL names the surface ``msg_kinds=("SnapshotChunk",)`` — not a
        pb wire type, so the pb-frame manglers never match it."""
        return "SnapshotChunk" in self.spec.msg_kinds and self.spec.kind in (
            "corrupt",
            "censor",
        )

    def applies_to_transfer_edge(self, a: int, b: int) -> bool:
        """Does this adversary attack transfer frames on edge a -> b?
        ``node`` scopes the compromised sender/link (-1 = any edge);
        ``victims`` optionally restricts the fetching side."""
        if not self.attacks_transfer():
            return False
        spec = self.spec
        if spec.victims and b not in spec.victims:
            return False
        return spec.node < 0 or spec.node == a

    def mangle_transfer(self, body: bytes):
        """Apply this adversary to one transfer frame body; returns None
        (untouched) or the replacement list.  Only CHUNK frames are
        attacked — they carry the snapshot bytes whose digest chain the
        fetcher must hold against exactly this adversary."""
        if not self.active() or not self.fires():
            return None
        try:
            kind, _pos = wire.decode_varint(body, 0)
        except ValueError:
            return None
        if kind != _KIND_CHUNK:
            return None
        if self.spec.kind == "censor":
            with self._lock:
                self.censored_transfer += 1
            return []
        # Corrupt: alternate bit-flips with tail truncation, both of
        # which the fetcher's chained digests must catch.
        with self._lock:
            truncate = len(body) > 2 and self._rng.random() < 0.5
        if truncate:
            mutated = body[: max(1, len(body) // 2)]
        else:
            mutated = self.flip(body)
        with self._lock:
            self.corrupted_transfer += 1
        return [mutated]

    def applies_to_mac_edge(self, a: int, b: int) -> bool:
        """Does this adversary forge MAC tags on directed edge a -> b?"""
        spec = self.spec
        if spec.kind != "forge_mac":
            return False
        if spec.victims and b not in spec.victims:
            return False
        return spec.node < 0 or spec.node == a

    def mangle_mac(self, payload: bytes):
        """Flip one byte of the frame's trailing MAC tag: the frame stays
        structurally parseable (varints and message body untouched), so
        the receiver's rejection is attributable to the authenticator
        check alone.  Returns the forged payload, or None to pass."""
        if not self.active() or not self.fires():
            return None
        if len(payload) <= _MAC_TAG_LEN:
            return None
        with self._lock:
            pos = len(payload) - 1 - self._rng.randrange(_MAC_TAG_LEN)
            mask = self._rng.randint(1, 255)
            self.forged_macs += 1
        forged = bytearray(payload)
        forged[pos] ^= mask
        return bytes(forged)

    def applies_to_edge(self, a: int, b: int) -> bool:
        """Does this adversary attack frames on directed edge a -> b?"""
        spec = self.spec
        if self.attacks_transfer():
            return False  # transfer-lane attack, not a pb wire attack
        if spec.kind == "equivocate":
            return spec.node == a and b in spec.victims
        if spec.kind == "censor":
            return spec.node == b
        if spec.kind in ("corrupt", "flood"):
            if spec.msg_kinds == ("Propose",):
                return False  # client-seam attack, not a wire attack
            return spec.node < 0 or spec.node == a
        return False

    def mangle_wire(self, msg: pb.Msg):
        """Apply this adversary to one framed message; returns None
        (untouched) or the replacement list."""
        spec = self.spec
        inner = msg.type
        if not self.active():
            return None
        if spec.kind == "equivocate":
            if not isinstance(inner, pb.Preprepare) or not inner.batch:
                return None
            if not self.fires():
                return None
            variant_batch = [
                pb.RequestAck(
                    client_id=ack.client_id,
                    req_no=ack.req_no,
                    digest=_variant_digest(ack.digest),
                )
                for ack in inner.batch
            ]
            with self._lock:
                self.variants[(inner.epoch, inner.seq_no)] = (
                    tuple(ack.digest for ack in inner.batch),
                    tuple(ack.digest for ack in variant_batch),
                )
            return [
                pb.Msg(
                    type=pb.Preprepare(
                        seq_no=inner.seq_no,
                        epoch=inner.epoch,
                        batch=variant_batch,
                    )
                )
            ]
        if spec.kind == "censor":
            if isinstance(inner, pb.RequestAck):
                pair = (inner.client_id, inner.req_no)
            elif isinstance(inner, pb.ForwardRequest):
                ack = inner.request_ack
                if ack is None:
                    return None
                pair = (ack.client_id, ack.req_no)
            else:
                return None
            if pair[0] not in spec.victims:
                return None
            with self._lock:
                self.censored += 1
                self.censored_pairs.add(pair)
            return []
        if not self.wire_kind_matches(msg) or not self.fires():
            return None
        if spec.kind == "flood":
            with self._lock:
                self.flooded += spec.copies
            return [msg] * (1 + spec.copies)
        if spec.kind == "corrupt":
            mutated = self._corrupt_msg(inner)
            if mutated is None:
                return None
            with self._lock:
                self.corrupted += 1
            return [pb.Msg(type=mutated)]
        return None

    def _corrupt_msg(self, inner):
        if isinstance(inner, pb.RequestAck):
            return pb.RequestAck(
                client_id=inner.client_id,
                req_no=inner.req_no,
                digest=self.flip(inner.digest),
            )
        if isinstance(inner, pb.Prepare):
            return pb.Prepare(
                seq_no=inner.seq_no,
                epoch=inner.epoch,
                digest=self.flip(inner.digest),
            )
        if isinstance(inner, pb.Commit):
            return pb.Commit(
                seq_no=inner.seq_no,
                epoch=inner.epoch,
                digest=self.flip(inner.digest),
            )
        if isinstance(inner, pb.ForwardRequest):
            return pb.ForwardRequest(
                request_ack=inner.request_ack,
                request_data=self.flip(inner.request_data),
            )
        if isinstance(inner, pb.Preprepare) and inner.batch:
            with self._lock:
                index = self._rng.randrange(len(inner.batch))
            batch = list(inner.batch)
            victim = batch[index]
            batch[index] = pb.RequestAck(
                client_id=victim.client_id,
                req_no=victim.req_no,
                digest=self.flip(victim.digest),
            )
            return pb.Preprepare(
                seq_no=inner.seq_no, epoch=inner.epoch, batch=batch
            )
        return None


class LiveCluster:
    """The driver: boots N replicas behind partition proxies, runs the
    paced client load, fires the scenario's fault schedule at scaled
    wall-clock instants, and reports convergence evidence."""

    def __init__(
        self,
        scenario: Scenario,
        seed: int,
        tick_seconds: float,
        budget_s: float,
        max_reqs_per_client: int,
        processor: str = "serial",
    ):
        self.scenario = scenario
        self.seed = seed
        self.tick_seconds = tick_seconds
        self.budget_s = budget_s
        # Executor kind every replica builds (Config.processor): the same
        # fault matrix must hold under serial, pooled, and pipelined.
        self.processor = processor
        # Live runs pay real fsyncs per commit; the deterministic matrix's
        # larger request counts (sized for client-window coverage) are
        # clamped so each scenario stays inside its wall-clock budget.
        self.reqs_per_client = min(scenario.reqs_per_client, max_reqs_per_client)
        # A scenario-supplied network state (e.g. a short max_epoch_length
        # for bucket-rotation scenarios) is mirrored into the live boot;
        # its client ids then ARE the live client ids, so client-targeted
        # adversaries mean the same thing under both engines.
        self._boot_state = (
            scenario.network_state() if scenario.network_state else None
        )
        if self._boot_state is not None:
            self.clients = [c.id for c in self._boot_state.clients]
        else:
            self.clients = list(range(1, scenario.client_count + 1))
        self.live_adversaries = [
            _LiveAdversary(spec, self, seed * 1013 + index)
            for index, spec in enumerate(scenario.adversaries)
        ]
        self._censors = [
            adv
            for adv in self.live_adversaries
            if adv.spec.kind == "censor" and not adv.attacks_transfer()
        ]
        self._propose_corrupters = [
            adv
            for adv in self.live_adversaries
            if adv.spec.kind == "corrupt"
            and adv.spec.msg_kinds == ("Propose",)
        ]
        self._propose_flooders = [
            adv
            for adv in self.live_adversaries
            if adv.spec.kind == "flood" and adv.spec.msg_kinds == ("Propose",)
        ]
        # (client_id, req_no) -> epoch rotations observed when the
        # censored request first committed anywhere (censorship-liveness
        # evidence, mirroring the deterministic runner).
        self.commit_rotations: dict = {}
        # MAC-authenticated replica channels: one cluster-wide secret
        # (derived from the seed so runs are reproducible); every
        # replica's transport derives per-link keys from it.
        self.auth_secret = (
            b"mirbft-live-auth-%d" % seed if scenario.link_auth else b""
        )
        self.root = tempfile.mkdtemp(prefix=f"mirbft-live-{scenario.name}-")
        self.replicas: list = [None] * scenario.node_count
        self.ports = [0] * scenario.node_count
        self.proxies: dict = {}  # (src, dst) -> PartitionProxy
        self.drop_fault = (
            DropFault(scenario.drop_pct, seed) if scenario.drop_pct else None
        )
        self._lock = threading.Lock()
        self.commit_times_ms: list = []
        self.heal_times_ms: list = []
        self.snapshots: list = []
        self.events_fired = 0
        self.requests: dict = {}
        self.signer = None
        self.plane = None
        self.forged_rejected = None
        self._start = None
        self._proposer_stop = threading.Event()
        self._proposer = None
        if scenario.signed:
            from ..testengine.signing import SignaturePlane, make_signer

            self.signer = make_signer()
            self.plane = (
                scenario.signature_plane()
                if scenario.signature_plane
                else SignaturePlane()
            )

    # -- time ----------------------------------------------------------------

    def scale_s(self, sim_ms: int) -> float:
        """Simulated ms (authored against the 500ms testengine tick) to
        wall seconds under this cluster's real tick period."""
        return sim_ms / SIM_TICK_MS * self.tick_seconds

    def now_ms(self) -> int:
        return int((time.monotonic() - self._start) * 1000)

    def _on_commit(self, _node_id: int, _nreqs: int) -> None:
        with self._lock:
            self.commit_times_ms.append(self.now_ms())

    # -- topology ------------------------------------------------------------

    def alive_replicas(self) -> list:
        return [r for r in self.replicas if r is not None]

    def boot(self) -> None:
        state = self._boot_state or standard_initial_network_state(
            self.scenario.node_count, self.clients
        )
        for n in range(self.scenario.node_count):
            self.replicas[n] = LiveReplica(self, n, initial_state=state)
            self.ports[n] = self.replicas[n].port
        for a in range(self.scenario.node_count):
            for b in range(self.scenario.node_count):
                if a != b:
                    upstream = self.replicas[b].transport.address
                    mangle = self._edge_mangler(a, b)
                    mangle_transfer = self._edge_transfer_mangler(a, b)
                    mangle_raw = self._edge_raw_mangler(a, b)
                    self.proxies[(a, b)] = (
                        AdversaryProxy(
                            upstream, mangle, mangle_transfer, mangle_raw
                        )
                        if mangle is not None
                        or mangle_transfer is not None
                        or mangle_raw is not None
                        else PartitionProxy(upstream)
                    )
        for replica in self.replicas:
            replica.wire()
            replica.start_consumer()

    def _edge_mangler(self, a: int, b: int):
        """Compose the wire-attacking adversaries for directed edge
        a -> b into one frame-mangle callback, or None for honest
        edges (which then get a plain byte-pumping PartitionProxy)."""
        advs = [
            adv
            for adv in self.live_adversaries
            if adv.applies_to_edge(a, b)
        ]
        if not advs:
            return None

        def mangle(_source: int, msg: pb.Msg):
            frames = [msg]
            changed = False
            for adv in advs:
                next_frames = []
                for frame in frames:
                    replacement = adv.mangle_wire(frame)
                    if replacement is None:
                        next_frames.append(frame)
                    else:
                        changed = True
                        next_frames.extend(replacement)
                frames = next_frames
            return frames if changed else None

        return mangle

    def _edge_transfer_mangler(self, a: int, b: int):
        """Compose the snapshot-transfer-stream adversaries for directed
        edge a -> b into one body-mangle callback, or None."""
        advs = [
            adv
            for adv in self.live_adversaries
            if adv.applies_to_transfer_edge(a, b)
        ]
        if not advs:
            return None

        def mangle_transfer(body: bytes):
            bodies = [body]
            changed = False
            for adv in advs:
                next_bodies = []
                for item in bodies:
                    replacement = adv.mangle_transfer(item)
                    if replacement is None:
                        next_bodies.append(item)
                    else:
                        changed = True
                        next_bodies.extend(replacement)
                bodies = next_bodies
            return bodies if changed else None

        return mangle_transfer

    def _edge_raw_mangler(self, a: int, b: int):
        """Compose the MAC-forging adversaries for directed edge a -> b
        into one raw-payload callback, or None.  Raw manglers see the
        undecoded node-lane frame payload (varints + body + MAC tag) and
        may return a replacement payload; they run before, and preempt,
        the message-level manglers for that frame."""
        advs = [
            adv
            for adv in self.live_adversaries
            if adv.applies_to_mac_edge(a, b)
        ]
        if not advs:
            return None

        def mangle_raw(source: int, payload: bytes):
            for adv in advs:
                forged = adv.mangle_mac(payload)
                if forged is not None:
                    return forged
            return None

        return mangle_raw

    def _edges_across(self, groups):
        group_of = {}
        for gi, group in enumerate(groups):
            for node in group:
                group_of[node] = gi
        for a in range(self.scenario.node_count):
            for b in range(self.scenario.node_count):
                if a != b and group_of.get(a) != group_of.get(b):
                    yield (a, b)

    def _set_partition(self, groups, cut: bool) -> None:
        for edge in self._edges_across(groups):
            self.proxies[edge].set_cut(cut)

    def _crash(self, node: int) -> None:
        replica = self.replicas[node]
        if replica is None:
            return
        self.snapshots.append(replica.snapshot(self.now_ms()))
        self.replicas[node] = None
        replica.kill()

    def _restart(self, node: int) -> None:
        if self.replicas[node] is not None:
            # A storage-fault victim whose fsync never fired (no persist
            # traffic): force the kill so the reboot still exercises
            # restart-from-disk.
            self._crash(node)
        replica = LiveReplica(self, node, initial_state=None, port=self.ports[node])
        replica.wire()
        replica.start_consumer()
        self.replicas[node] = replica
        with self._lock:
            self.heal_times_ms.append(self.now_ms())

    # -- client load ---------------------------------------------------------

    def start_proposer(self, last_event_s: float) -> None:
        self._proposer = threading.Thread(
            target=self._propose_all,
            args=(last_event_s,),
            name="chaos-live-proposer",
            daemon=True,
        )
        self._proposer.start()

    def _propose_all(self, last_event_s: float) -> None:
        requests: dict = {}
        for req_no in range(self.reqs_per_client):
            for client_id in self.clients:
                payload = b"%d" % req_no
                data = (
                    self.signer(client_id, req_no, payload)
                    if self.signer is not None
                    else payload
                )
                requests[(client_id, req_no)] = data
        self.requests = requests
        # Pace the initial pass past the last fault instant so every
        # disruption lands mid-traffic AND a tail of fresh proposals
        # arrives after the final heal — the commit-resumption invariant
        # measures real post-heal progress, not leftovers.
        span_s = max(last_event_s * 1.25, 0.4)
        gap = span_s / max(len(requests), 1)
        for (client_id, req_no), data in requests.items():
            if self._proposer_stop.wait(gap):
                return
            if self.plane is not None and not self.plane.valid(
                client_id, req_no, data
            ):
                continue  # ingress auth rejected (never for honest clients)
            for replica in self.alive_replicas():
                self._adversarial_deliver(replica, client_id, req_no, data)
        if self.plane is not None:
            # Ingress authentication must stop a forged request cold: the
            # real payload with one signature byte flipped.
            client_id = self.clients[0]
            good = requests[(client_id, 0)]
            forged = good[:-96] + bytes([good[-96] ^ 0xFF]) + good[-95:]
            self.forged_rejected = not self.plane.valid(client_id, 0, forged)
        # Client retry: keep nudging stragglers (restarted nodes, frames
        # lost to drops/partitions) until the driver declares convergence.
        # Re-proposing an already-committed req_no is safe: the ack
        # filter drops below-watermark acks as PAST.
        while not self._proposer_stop.wait(0.3):
            for replica in self.alive_replicas():
                committed = {(c, q) for c, q, _s in replica.app_log.commits}
                for (client_id, req_no), data in requests.items():
                    if (client_id, req_no) not in committed:
                        self._adversarial_deliver(
                            replica, client_id, req_no, data
                        )
                # Stale-echo flood: while the attack window is open, an
                # already-committed request is re-submitted per round —
                # the live analogue of the DSL's delayed echoes, which
                # watermark dedup must drop as PAST.
                for adv in self._propose_flooders:
                    if committed and adv.active() and adv.fires():
                        client_id, req_no = next(iter(committed))
                        self._propose_one(
                            replica,
                            client_id,
                            req_no,
                            requests.get((client_id, req_no), b""),
                        )
                        with adv._lock:
                            adv.flooded += 1

    def _adversarial_deliver(self, replica, client_id, req_no, data) -> None:
        """One client->replica delivery through the adversary layer:
        censoring leaders never learn the request, corrupted deliveries
        must die at the ingress signature gate, flooded deliveries are
        multiplied."""
        for adv in self._censors:
            if (
                replica.node_id == adv.spec.node
                and client_id in adv.spec.victims
                and adv.active()
            ):
                with adv._lock:
                    adv.censored += 1
                    adv.censored_pairs.add((client_id, req_no))
                return
        for adv in self._propose_corrupters:
            spec = adv.spec
            if (
                (not spec.victims or replica.node_id in spec.victims)
                and adv.active()
                and adv.fires()
            ):
                bad = adv.flip(data)
                with adv._lock:
                    adv.corrupted += 1
                    adv.corrupted_proposes += 1
                if self.plane is not None and not self.plane.valid(
                    client_id, req_no, bad
                ):
                    with adv._lock:
                        adv.rejections += 1
                    return  # ingress auth refused the corrupted delivery
                # Unsigned (or a verification hole): the corrupted bytes
                # go in and the digest audit must catch any divergence.
                self._propose_one(replica, client_id, req_no, bad)
                return
        self._propose_one(replica, client_id, req_no, data)
        for adv in self._propose_flooders:
            if adv.active() and adv.fires():
                for _ in range(adv.spec.copies):
                    self._propose_one(replica, client_id, req_no, data)
                with adv._lock:
                    adv.flooded += adv.spec.copies

    def _propose_one(self, replica, client_id, req_no, data) -> None:
        try:
            replica.node.propose(
                pb.Request(client_id=client_id, req_no=req_no, data=data)
            )
        except (NodeStopped, ValueError):
            pass  # node stopped/crashed concurrently: the retry pass covers it

    # -- the drive loop ------------------------------------------------------

    def schedule(self) -> list:
        events = []
        for window in self.scenario.partitions:
            events.append((self.scale_s(window.from_ms), 0, "cut", window.groups))
            events.append((self.scale_s(window.until_ms), 1, "heal", window.groups))
        for point in self.scenario.crashes:
            events.append((self.scale_s(point.at_ms), 2, "crash", point.node))
            events.append(
                (
                    self.scale_s(point.at_ms + point.restart_delay_ms),
                    3,
                    "restart",
                    point.node,
                )
            )
        for fault in self.scenario.storage_faults:
            events.append(
                (self.scale_s(fault.at_ms), 4, "storage_fault", fault.node)
            )
            events.append(
                (
                    self.scale_s(fault.at_ms + fault.restart_delay_ms),
                    5,
                    "restart",
                    fault.node,
                )
            )
        events.sort(key=lambda e: (e[0], e[1]))
        return events

    def _fire(self, kind: str, payload, armed: set) -> None:
        if kind == "cut":
            self._set_partition(payload, True)
        elif kind == "heal":
            self._set_partition(payload, False)
            with self._lock:
                self.heal_times_ms.append(self.now_ms())
        elif kind == "crash":
            self._crash(payload)
        elif kind == "storage_fault":
            replica = self.replicas[payload]
            if replica is not None:
                replica.arm_storage_fault()
                armed.add(payload)
        elif kind == "restart":
            self._restart(payload)

    def _reap(self, armed: set) -> None:
        """Crash-kill replicas whose consumer died on an injected storage
        fault; any *uninjected* death is a real bug and fails the run."""
        for n, replica in enumerate(self.replicas):
            if replica is None:
                continue
            if replica.failed is not None:
                if n in armed:
                    self._crash(n)
                else:
                    raise InvariantViolation(
                        f"node {n} consumer died without an injected fault: "
                        f"{replica.failed!r}"
                    )
            elif replica.node.exit_error is not None:
                raise InvariantViolation(
                    f"node {n} serializer died: {replica.node.exit_error!r}"
                )

    def _converged(self, expected: set) -> bool:
        """The TCP-tier convergence criterion: every node is up, at least
        one committed the full request set, and all app chains agree (a
        restarted node may have adopted part of the history via state
        transfer rather than committing it individually)."""
        replicas = list(self.replicas)
        if any(r is None for r in replicas):
            return False
        full = False
        chains = set()
        for replica in replicas:
            pairs = {(c, q) for c, q, _s in replica.app_log.commits}
            if expected <= pairs:
                full = True
            chains.add(replica.app_log.chain)
        return full and len(chains) == 1 and b"" not in chains

    def run(self) -> int:
        """Boot, drive the schedule, and return the convergence instant
        (wall ms since start); raises InvariantViolation on timeout or an
        uninjected node death."""
        self._start = time.monotonic()
        self.boot()
        events = self.schedule()
        last_event_s = events[-1][0] if events else 0.0
        self.start_proposer(last_event_s)
        expected = {
            (client_id, req_no)
            for client_id in self.clients
            for req_no in range(self.reqs_per_client)
        }
        deadline = self._start + self.budget_s
        armed: set = set()
        next_censor_poll = 0.0
        while time.monotonic() < deadline:
            now_s = time.monotonic() - self._start
            while events and events[0][0] <= now_s:
                _at, _order, kind, payload = events.pop(0)
                self.events_fired += 1
                self._fire(kind, payload, armed)
            self._reap(armed)
            if self._censors and now_s >= next_censor_poll:
                next_censor_poll = now_s + 0.2
                self._track_censored_commits()
            if not events and self._converged(expected):
                if self._censors:
                    self._track_censored_commits()
                return self.now_ms()
            time.sleep(0.01)
        commits = [
            len(r.app_log.commits) if r is not None else None
            for r in self.replicas
        ]
        raise InvariantViolation(
            f"no convergence within the {self.budget_s:.0f}s budget "
            f"(per-node commits: {commits}, epochs: {self._epoch_states()}, "
            f"events unfired: {len(events)})"
        )

    def _current_rotation(self) -> int:
        """Epoch rotations past the boot-negotiated working epoch, read
        from the obsv ``epoch.active`` milestone labels — the same
        telemetry an operator would watch."""
        best = 0
        if hooks.enabled:
            snap = hooks.metrics.snapshot().get("mirbft_epoch_events_total")
            if snap:
                for series in snap["series"]:
                    labels = series["labels"]
                    if labels.get("event") != "active":
                        continue
                    try:
                        best = max(best, int(labels.get("epoch", "0")))
                    except ValueError:
                        continue
        return max(0, best - FIRST_WORKING_EPOCH)

    def _track_censored_commits(self) -> None:
        pending: set = set()
        for adv in self._censors:
            with adv._lock:
                pending |= adv.censored_pairs
        pending -= set(self.commit_rotations)
        if not pending:
            return
        committed: set = set()
        for replica in self.alive_replicas():
            committed |= {
                (c, q) for c, q, _s in list(replica.app_log.commits)
            }
        rotation = None
        for pair in pending:
            if pair in committed:
                if rotation is None:
                    rotation = self._current_rotation()
                self.commit_rotations[pair] = rotation

    def _epoch_states(self) -> list:
        """Per-node ``epoch/state`` diagnostic strings for the timeout
        report (a wedged epoch change reads very differently from a
        transport-level stall)."""
        states = []
        for replica in self.replicas:
            if replica is None:
                states.append("down")
                continue
            try:
                status = replica.node.status(timeout=2.0)
            except NodeStopped:
                status = None
            if status is None or status.epoch_tracker is None:
                states.append("?")
            else:
                et = status.epoch_tracker
                states.append(f"{et.number}/{et.state}")
        return states

    def teardown(self) -> None:
        self._proposer_stop.set()
        if self._proposer is not None and self._proposer.ident is not None:
            self._proposer.join(timeout=10)
        for n, replica in enumerate(self.replicas):
            if replica is not None:
                self.replicas[n] = None
                replica.kill(graceful=True)
        for proxy in self.proxies.values():
            proxy.close()
        shutil.rmtree(self.root, ignore_errors=True)


class _LiveEvidence:
    """Adapter handing the shared invariant checkers (invariants.py) the
    recorder-shaped view they audit, backed by the cluster's durable
    per-node commit logs."""

    def __init__(self, replicas: list):
        self.node_count = len(replicas)
        self.node_states = [
            SimpleNamespace(
                committed_reqs=list(replica.app_log.commits),
                app_chain=replica.app_log.chain,
                crashed=False,
            )
            for replica in replicas
        ]
        # client_id -> committed_anywhere req_no set, for the
        # censorship-liveness audit.
        anywhere: dict = {}
        for state in self.node_states:
            for client_id, req_no, _seq in state.committed_reqs:
                anywhere.setdefault(client_id, set()).add(req_no)
        self.clients = {
            client_id: SimpleNamespace(committed_anywhere=req_nos)
            for client_id, req_nos in anywhere.items()
        }


def _epoch_active_total(registry) -> int:
    """Count obsv ``epoch.active`` milestone events for epochs *beyond*
    the boot-negotiated working epoch.  Every run activates
    FIRST_WORKING_EPOCH at startup (the bootstrap WAL's FEntry ends epoch
    0, so the cluster negotiates epoch 1 before the first commit), so
    only later activations are evidence of a forced change."""
    snap = registry.snapshot().get("mirbft_epoch_events_total")
    if not snap:
        return 0
    total = 0
    for series in snap["series"]:
        labels = series["labels"]
        if labels.get("event") != "active":
            continue
        try:
            epoch = int(labels.get("epoch", "0"))
        except ValueError:
            continue
        if epoch > FIRST_WORKING_EPOCH:
            total += series["value"]
    return int(total)


def _audit_live_adversaries(scenario, cluster, registry, result) -> None:
    """Run the Byzantine invariants over the live evidence — the same
    checkers the deterministic runner uses, fed from the cluster's
    durable commit logs and the adversaries' attack counters.  Raises
    InvariantViolation."""
    advs = cluster.live_adversaries
    if not advs and not scenario.link_auth and not scenario.cert_audit:
        return
    corrupted = sum(adv.corrupted for adv in advs)
    corrupted_proposes = sum(adv.corrupted_proposes for adv in advs)
    rejections = sum(adv.rejections for adv in advs)
    flooded = sum(adv.flooded for adv in advs)
    censored = sum(adv.censored for adv in advs)
    variants: dict = {}
    censored_pairs: set = set()
    for adv in advs:
        variants.update(adv.variants)
        censored_pairs |= adv.censored_pairs
    evidence = _LiveEvidence(cluster.replicas)

    if corrupted:
        result.counters["corrupted"] = corrupted
    if scenario.signed and corrupted_proposes:
        result.counters["rejections"] = rejections
        check_corruption_rejected(rejections, corrupted_proposes)
    if variants:
        result.counters["equivocated"] = len(variants)
        # Suspicion (expect_epoch_change) is asserted separately via the
        # epoch.active milestones, which live nodes emit; here the live
        # audit holds the no-fork half of the equivocation invariant.
        check_no_fork_under_equivocation(
            evidence, variants, expect_suspicion=False
        )
    if cluster._censors:
        result.counters["censored"] = censored
        k = scenario.notes.get("censor_k", 3)
        check_censorship_liveness(
            evidence, censored_pairs, cluster.commit_rotations, k
        )
        rotations = list(cluster.commit_rotations.values())
        result.counters["rotations_max"] = max(rotations, default=0)
        histogram = registry.histogram(
            "mirbft_censored_commit_epochs",
            buckets=ROTATION_BUCKETS,
            scenario=scenario.name,
        )
        for rotation in rotations:
            histogram.observe(rotation)
    if any(adv.attacks_transfer() for adv in advs):
        transfer_corrupted = sum(adv.corrupted_transfer for adv in advs)
        transfer_censored = sum(adv.censored_transfer for adv in advs)
        result.counters["transfer_corrupted"] = transfer_corrupted
        result.counters["transfer_censored"] = transfer_censored
        rejected = sum(
            replica.engine.counters["chunks_rejected_corrupt"]
            for replica in cluster.alive_replicas()
        )
        result.counters["transfer_rejected"] = rejected
        if transfer_corrupted:
            check_transfer_corruption_rejected(rejected, transfer_corrupted)
        elif transfer_censored <= 0:
            raise InvariantViolation(
                "transfer attack touched no frames (vacuous)"
            )
    if any(adv.spec.kind == "flood" for adv in advs):
        result.counters["flooded"] = flooded
        if flooded <= 0:
            raise InvariantViolation(
                "flood scenario injected no echoes (vacuous)"
            )
        # Exactly-once is already held by check_no_fork on the durable
        # logs; bounded memory is held at the request-store seam (echoes
        # deduplicate to at most one pending entry per distinct request).
        total = len(cluster.clients) * cluster.reqs_per_client
        for replica in cluster.alive_replicas():
            pending = replica.reqstore.pending_count()
            if pending > total:
                raise InvariantViolation(
                    f"flood grew node {replica.node_id}'s request store "
                    f"to {pending} pending entries for {total} distinct "
                    "requests"
                )
    if scenario.link_auth and any(
        adv.spec.kind == "forge_mac" for adv in advs
    ):
        forged = sum(adv.forged_macs for adv in advs)
        mac_rejections = sum(
            sum(replica.transport.mac_rejections.values())
            for replica in cluster.alive_replicas()
        )
        result.counters["forged_macs"] = forged
        result.counters["mac_rejections"] = mac_rejections
        # Live audit is lossy (a forged frame can die with a torn-down
        # connection before the receiver's MAC check sees it), so the
        # bound is 0 < rejections <= forged; the none-accepted half is
        # held by no-fork/convergence on the durable logs.
        check_mac_rejected(mac_rejections, forged, exact=False)
    if scenario.cert_audit:
        _audit_live_certs(scenario, cluster, result)


def _audit_live_certs(scenario, cluster, result) -> None:
    """Re-derive aggregate checkpoint certificates from the live nodes'
    captured checkpoints and run the forgery audit through the qc seam:
    every quorum of matching stable checkpoints yields one BLS aggregate
    certificate, each genuine certificate must verify, and per-cert
    forgeries (mismatched statement, wrong signer set) must all be
    rejected.  Raises InvariantViolation."""
    from ..crypto import qc
    from ..testengine.certs import node_seed, statement

    f = (scenario.node_count - 1) // 3
    quorum = 2 * f + 1
    stable: dict = {}
    for replica in cluster.alive_replicas():
        for seq, (value, _state) in replica.checkpoints.items():
            stable.setdefault((seq, value), set()).add(replica.node_id)
    certs: dict = {}
    for (seq, value), nodes in sorted(stable.items()):
        signers = tuple(sorted(nodes)[:quorum])
        if len(signers) < quorum:
            continue
        votes = [
            qc.sign_vote(node_seed(n), statement(seq, value))
            for n in signers
        ]
        certs[(seq, value)] = (
            signers,
            qc.aggregate(votes, use_device=False),
        )
    if not certs:
        raise InvariantViolation(
            "cert audit found no quorum-stable checkpoints (vacuous)"
        )
    genuine_ok, genuine_total, forged_rejected, forged_total = (
        audit_aggregate_certs(certs)
    )
    result.counters["certs"] = genuine_total
    result.counters["cert_forgeries_rejected"] = forged_rejected
    check_aggregate_cert_rejected(
        genuine_ok, genuine_total, forged_rejected, forged_total
    )


def run_live_scenario(
    scenario: Scenario,
    seed: int = 0,
    registry: Registry | None = None,
    tick_seconds: float = 0.04,
    budget_s: float = 90.0,
    max_reqs_per_client: int = 40,
    processor: str = "serial",
) -> ScenarioResult:
    """Execute one scenario against a real loopback cluster and audit
    every invariant.  Invariant violations are reported in the result,
    never raised; harness bugs propagate.

    Observability is required (epoch milestones and transport counters
    are part of the evidence): if hooks are not already enabled, they are
    enabled around the run with ``registry`` (or a fresh one) and
    restored after."""
    own_hooks = not hooks.enabled
    if own_hooks:
        hooks.enable(
            registry=registry if registry is not None else Registry(),
            trace=False,
        )
    registry = hooks.metrics
    # Reuse the session flight recorder if one is wired; otherwise lend a
    # scenario-local ring to the hooks so node milestones land in the
    # postmortem dump attached on invariant failure.
    recorder = hooks.recorder
    own_recorder = recorder is None
    if own_recorder:
        recorder = FlightRecorder(f"chaos-live-{scenario.name}")
        hooks.recorder = recorder
    recorder.record_note(
        "scenario.start", args={"scenario": scenario.name, "seed": seed}
    )
    result = ScenarioResult(name=scenario.name, seed=seed, passed=False)
    epoch_active_before = _epoch_active_total(registry)
    cluster = LiveCluster(
        scenario,
        seed,
        tick_seconds,
        budget_s,
        max_reqs_per_client,
        processor=processor,
    )
    try:
        try:
            converged_ms = cluster.run()
            heals = cluster.heal_times_ms
            last_heal = max(heals) if heals else 0
            bound_ms = max(
                int(cluster.scale_s(scenario.recovery_bound_ms) * 1000),
                MIN_RECOVERY_BOUND_MS,
            )
            gauge = registry.gauge(
                "mirbft_chaos_live_recovery_ms", scenario=scenario.name
            )
            gauge.set(converged_ms - last_heal)
            result.counters["recovery_ms"] = gauge.value
            check_bounded_recovery(converged_ms, last_heal, bound_ms)
            if heals:
                check_commit_resumption(
                    cluster.commit_times_ms, last_heal, bound_ms
                )
            evidence = _LiveEvidence(cluster.replicas)
            check_no_fork(evidence)
            check_durable_prefix(evidence, cluster.snapshots)
            # Live form of check_no_vector_divergence: the oracle must run
            # on each serializer thread (the tracker is thread-confined),
            # so ask every live node to audit itself.
            divergences = 0
            for replica in cluster.alive_replicas():
                try:
                    divs = replica.node.audit_divergence(timeout=5.0)
                except Exception:
                    divs = None  # stopping/stopped replica: nothing to audit
                if divs:
                    divergences += len(divs)
                    first = divs[0]
                    raise InvariantViolation(
                        f"node {replica.node_id}: vector ack path diverged "
                        f"from the scalar reference in {len(divs)} place(s); "
                        f"first: {first['component']} at client "
                        f"{first['client_id']} req_no {first['req_no']} "
                        f"({first['detail']})"
                    )
            result.counters["divergences"] = divergences
            if scenario.expect_epoch_change:
                delta = _epoch_active_total(registry) - epoch_active_before
                result.counters["epoch_active_events"] = delta
                if delta <= 0:
                    raise InvariantViolation(
                        "scenario expected an epoch change but the obsv "
                        "epoch.active milestone never fired past the boot "
                        f"epoch ({FIRST_WORKING_EPOCH})"
                    )
                epochs = []
                for replica in cluster.alive_replicas():
                    status = replica.node.status(timeout=5.0)
                    if status is not None and status.epoch_tracker is not None:
                        epochs.append(status.epoch_tracker.number)
                result.counters["epoch"] = max(epochs) if epochs else 0
                # Every run negotiates FIRST_WORKING_EPOCH at boot, so a
                # node still there has seen no change at all.
                if not epochs or max(epochs) <= FIRST_WORKING_EPOCH:
                    raise InvariantViolation(
                        "scenario expected an epoch change but every node "
                        f"still reports the boot epoch (epochs {epochs})"
                    )
            if cluster.plane is not None:
                result.counters["sig_device_errors"] = (
                    cluster.plane.device_errors
                )
                result.counters["sig_fallbacks"] = (
                    cluster.plane.fallback_verifies
                )
                result.counters["sig_breaker"] = cluster.plane.breaker.state
                if cluster.forged_rejected is not True:
                    raise InvariantViolation(
                        "a forged request passed ingress signature "
                        "verification"
                    )
            _audit_live_adversaries(scenario, cluster, registry, result)
            result.passed = True
        except InvariantViolation as violation:
            result.violation = str(violation)
            result.dump = dump_on_violation(
                recorder, scenario.name, seed, violation
            )
        result.events = cluster.events_fired
        result.sim_ms = cluster.now_ms() if cluster._start is not None else 0
        result.commits = sum(
            len(replica.app_log.commits)
            for replica in cluster.alive_replicas()
        )
        if cluster.snapshots:
            result.counters["crashes"] = len(cluster.snapshots)
        tcp = {"connects": 0, "connect_failures": 0, "send_failures": 0}
        dropped_fault = 0
        for replica in cluster.alive_replicas():
            counters = replica.transport.counters()
            dropped_fault += counters["dropped_fault"]
            for peer in counters["peers"].values():
                tcp["connects"] += peer["connects"]
                tcp["connect_failures"] += peer["connect_failures"]
                tcp["send_failures"] += peer["send_failures"]
        result.counters["tcp_connects"] = tcp["connects"]
        if tcp["connect_failures"]:
            result.counters["tcp_connect_failures"] = tcp["connect_failures"]
        if tcp["send_failures"]:
            result.counters["tcp_send_failures"] = tcp["send_failures"]
        if dropped_fault:
            result.counters["dropped_fault"] = dropped_fault
    finally:
        cluster.teardown()
        if own_recorder and hooks.recorder is recorder:
            hooks.recorder = None
        if own_hooks:
            hooks.disable()
    return result


def run_live_campaign(
    scenarios: list | None = None,
    seed: int = 0,
    tick_seconds: float = 0.04,
    budget_s: float = 90.0,
    processor: str = "serial",
) -> CampaignResult:
    """Run a scenario list (default: the live matrix) against real
    clusters, one at a time, under derived per-scenario seeds."""
    if scenarios is None:
        scenarios = live_matrix()
    campaign = CampaignResult(seed=seed)
    for index, scenario in enumerate(scenarios):
        campaign.results.append(
            run_live_scenario(
                scenario,
                seed=seed + index,
                tick_seconds=tick_seconds,
                budget_s=budget_s,
                processor=processor,
            )
        )
    return campaign
