"""Device-plane fault injection for the chaos campaign.

The crypto planes (testengine/crypto_plane.py, testengine/signing.py) take
a pluggable backend; ``FlakyDigestBackend`` wraps one with a deterministic
call-indexed failure window so a scenario can make the "device" die, lie
(short reads), or hang (exceed the plane's deadline) for a stretch of the
run and then recover — exercising the circuit breaker's trip → fallback →
probe → re-close cycle without any wall-clock nondeterminism in *what*
fails (only call indices decide)."""

from __future__ import annotations

import time

from ..testengine.crypto_plane import DevicePlaneError, _host_digest_many
from ..testengine.signing import host_verifier

MODES = ("die", "short", "slow")


class FlakyDigestBackend:
    """A ``digest_many``-compatible callable that misbehaves for calls
    ``fail_from <= i < fail_until`` (0-indexed) and is healthy otherwise.

    Modes:

    - ``die``:   raise DevicePlaneError (device lost mid-wave).
    - ``short``: return half the digests (a lying readback).
    - ``slow``:  sleep ``delay_s`` before answering correctly — pair with
      a plane ``timeout_s`` below ``delay_s`` so the breaker counts it.

    While the plane's breaker is open the backend is only reached by
    probes, so the call index — and therefore the recovery point — stays
    deterministic for a given scenario.
    """

    def __init__(
        self,
        fail_from: int = 0,
        fail_until: int = 0,
        mode: str = "die",
        delay_s: float = 0.002,
        backend=None,
    ):
        assert mode in MODES, f"mode must be one of {MODES}"
        self.fail_from = fail_from
        self.fail_until = fail_until
        self.mode = mode
        self.delay_s = delay_s
        self.backend = backend if backend is not None else _host_digest_many
        self.calls = 0
        self.injected = 0

    def __call__(self, msgs: list) -> list:
        index = self.calls
        self.calls += 1
        if self.fail_from <= index < self.fail_until:
            self.injected += 1
            if self.mode == "die":
                raise DevicePlaneError(
                    f"injected device loss (call {index})"
                )
            if self.mode == "short":
                return self.backend(msgs)[: len(msgs) // 2]
            time.sleep(self.delay_s)
        return self.backend(msgs)


class FlakyVerifierBackend:
    """The signature-plane twin of FlakyDigestBackend: a
    ``host_verifier``-compatible callable (items of
    ``(client_id, req_no, data)`` -> verdicts) that misbehaves for calls
    ``fail_from <= i < fail_until`` and is healthy otherwise.  Same
    call-indexed determinism: while the plane's breaker is open only
    probes reach the backend, so the recovery point is fixed per
    scenario."""

    def __init__(
        self,
        fail_from: int = 0,
        fail_until: int = 0,
        mode: str = "die",
        delay_s: float = 0.002,
        backend=None,
    ):
        assert mode in MODES, f"mode must be one of {MODES}"
        self.fail_from = fail_from
        self.fail_until = fail_until
        self.mode = mode
        self.delay_s = delay_s
        self.backend = backend if backend is not None else host_verifier
        self.calls = 0
        self.injected = 0

    def __call__(self, items: list) -> list:
        index = self.calls
        self.calls += 1
        if self.fail_from <= index < self.fail_until:
            self.injected += 1
            if self.mode == "die":
                raise DevicePlaneError(
                    f"injected verifier loss (call {index})"
                )
            if self.mode == "short":
                return self.backend(items)[: len(items) // 2]
            time.sleep(self.delay_s)
        return self.backend(items)
