"""Chaos campaign harness: seeded fault-scenario matrix + invariants.

The testengine's mangler DSL (testengine/manglers.py) injects individual
faults; this package turns it into a *campaign*: a reproducible matrix of
scenarios — message loss, jitter, duplication, crash + restart schedules,
network partitions with heal, and device-plane faults against the crypto
planes — each executed under a seeded Recorder and then audited by an
invariant checker:

- **No fork**: committed prefixes agree across nodes (any two nodes that
  committed a sequence number committed the same requests there, in the
  same order).
- **Durability**: a crashed node's post-replay commit log is a
  prefix-consistent continuation of what it had committed before the
  crash.
- **Bounded recovery**: the run converges within a bound of the last
  disruption (partition heal / node restart) — liveness degrades, never
  dies.

Entry points::

    python -m mirbft_tpu.chaos                 # full matrix
    python -m mirbft_tpu.chaos --smoke         # the tier-1 subset
    python -m mirbft_tpu.chaos --seed 7 --only partition

See docs/CHAOS.md for the scenario catalogue.
"""

from .faults import FlakyDigestBackend
from .invariants import (
    CrashSnapshot,
    InvariantViolation,
    check_bounded_recovery,
    check_durable_prefix,
    check_full_convergence,
    check_no_fork,
)
from .runner import CampaignResult, ScenarioResult, run_campaign, run_scenario
from .scenarios import SMOKE_NAMES, CrashPoint, Scenario, matrix, smoke_matrix

__all__ = [
    "CampaignResult",
    "CrashPoint",
    "CrashSnapshot",
    "FlakyDigestBackend",
    "InvariantViolation",
    "Scenario",
    "ScenarioResult",
    "SMOKE_NAMES",
    "check_bounded_recovery",
    "check_durable_prefix",
    "check_full_convergence",
    "check_no_fork",
    "matrix",
    "run_campaign",
    "run_scenario",
    "smoke_matrix",
]
