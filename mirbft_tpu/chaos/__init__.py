"""Chaos campaign harness: seeded fault-scenario matrix + invariants,
runnable on two engines.

The testengine's mangler DSL (testengine/manglers.py) injects individual
faults; this package turns it into a *campaign*: a reproducible matrix of
scenarios — message loss, jitter, duplication, crash + restart schedules,
network partitions with heal, epoch-change-targeted leader isolation,
device-plane faults against the crypto planes, and signed-mode verifier
faults — each audited by an invariant checker:

- **No fork**: committed prefixes agree across nodes (any two nodes that
  committed a sequence number committed the same requests there, in the
  same order).
- **Durability**: a crashed node's post-replay commit log is a
  prefix-consistent continuation of what it had committed before the
  crash.
- **Bounded recovery**: the run converges within a bound of the last
  disruption (partition heal / node restart) — liveness degrades, never
  dies.
- **Commit resumption**: after the last heal/restart, the cluster
  *resumes committing* within the bound, not merely "eventually".

One scenario schema, two engines: the deterministic runner (runner.py)
lowers scenarios onto the simulated testengine, while the live driver
(live.py) lowers the same scenarios onto a real loopback TCP cluster —
real ``runtime.Node`` threads, socket-level partition proxies, crash-kill
+ ``Node.restart`` from on-disk WALs, and failing fsyncs.

Entry points::

    python -m mirbft_tpu.chaos                 # full deterministic matrix
    python -m mirbft_tpu.chaos --smoke         # the tier-1 subset
    python -m mirbft_tpu.chaos --live          # real-cluster campaign
    python -m mirbft_tpu.chaos --live --smoke  # tier-1 live smoke
    python -m mirbft_tpu.chaos --seed 7 --only partition

See docs/CHAOS.md for the scenario catalogue and the live-mode knobs.
"""

from .faults import FlakyDigestBackend, FlakyVerifierBackend
from .invariants import (
    CrashSnapshot,
    InvariantViolation,
    check_bounded_recovery,
    check_censorship_liveness,
    check_commit_resumption,
    check_corruption_rejected,
    check_durable_prefix,
    check_flood_bounded,
    check_full_convergence,
    check_no_fork,
    check_no_fork_under_equivocation,
)
from .live import (
    AdversaryProxy,
    DurableChainLog,
    LiveCluster,
    PartitionProxy,
    run_live_campaign,
    run_live_scenario,
)
from .runner import CampaignResult, ScenarioResult, run_campaign, run_scenario
from .scenarios import (
    ADVERSARY_SMOKE_NAMES,
    LIVE_ADVERSARY_NAMES,
    LIVE_SMOKE_NAMES,
    SMOKE_NAMES,
    Adversary,
    CrashPoint,
    PartitionWindow,
    Scenario,
    StorageFault,
    adversary_matrix,
    adversary_smoke_matrix,
    live_adversary_matrix,
    live_matrix,
    live_smoke_matrix,
    matrix,
    smoke_matrix,
)

__all__ = [
    "ADVERSARY_SMOKE_NAMES",
    "Adversary",
    "AdversaryProxy",
    "CampaignResult",
    "CrashPoint",
    "CrashSnapshot",
    "DurableChainLog",
    "FlakyDigestBackend",
    "FlakyVerifierBackend",
    "InvariantViolation",
    "LIVE_ADVERSARY_NAMES",
    "LIVE_SMOKE_NAMES",
    "LiveCluster",
    "PartitionProxy",
    "PartitionWindow",
    "Scenario",
    "ScenarioResult",
    "SMOKE_NAMES",
    "StorageFault",
    "adversary_matrix",
    "adversary_smoke_matrix",
    "check_bounded_recovery",
    "check_censorship_liveness",
    "check_commit_resumption",
    "check_corruption_rejected",
    "check_durable_prefix",
    "check_flood_bounded",
    "check_full_convergence",
    "check_no_fork",
    "check_no_fork_under_equivocation",
    "live_adversary_matrix",
    "live_matrix",
    "live_smoke_matrix",
    "matrix",
    "run_campaign",
    "run_live_campaign",
    "run_live_scenario",
    "run_scenario",
    "smoke_matrix",
]
