"""Deterministic binary codec for the schema layer.

The reference serializes everything with protobuf (reference:
mirbftpb/mirbft.proto).  This framework is not wire-compatible with the Go
implementation; instead it defines its own *canonical* encoding with the one
property the whole test methodology depends on: encoding is a pure function of
the message value (no maps, no presence-dependent field skipping, no varint
malleability accepted on decode).  Every event log, WAL entry, and hash
preimage in the framework goes through this module, which is what makes runs
recordable and replayable bit-for-bit (reference: docs/StateMachine.md, the
determinism discipline).

Messages declare an explicit ``_spec_``: a tuple of (field_name, FieldType)
pairs, encoded in declaration order.  Supported field types are built from:

- ``U64`` / ``U32`` / ``I32`` — unsigned LEB128 varints (I32 values must be
  non-negative; the reference only uses non-negative int32s).
- ``BOOL`` — one byte, 0 or 1.
- ``BYTES`` — varint length + raw bytes.
- ``Nested(cls)`` — optional nested message: presence byte, then varint
  length + body.  ``None`` encodes as a single 0 byte.
- ``Rep(ft)`` — repeated field: varint count + encoded items.
- ``OneOf((tag, cls), ...)`` — tagged union: varint tag + varint length +
  body.  Tag 0 means unset, accepted only when ``allow_unset`` (the default);
  oneofs where an empty value is never legitimate (Msg, Persistent,
  StateEvent, Reconfiguration) set ``allow_unset=False`` and reject it.
"""

from __future__ import annotations

import io
from dataclasses import fields as dc_fields
from typing import Any


def encode_varint(value: int) -> bytes:
    if value < 0:
        raise ValueError(f"varint must be non-negative, got {value}")
    if value >> 64:
        raise ValueError(f"varint exceeds 64 bits: {value}")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            # Reject non-canonical (over-long) encodings so that
            # encode(decode(x)) == x for every accepted input.
            if b == 0 and shift != 0:
                raise ValueError("non-canonical varint")
            # A 10th byte may only contribute bit 63: the decodable set must
            # equal the encodable set (values < 2^64) at every position,
            # including raw length/count/tag positions.
            if shift == 63 and b > 1:
                raise ValueError("varint exceeds 64 bits")
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


class FieldType:
    def encode(self, out: io.BytesIO, value: Any) -> None:
        raise NotImplementedError

    def decode(self, buf: bytes, pos: int) -> tuple[Any, int]:
        raise NotImplementedError


class _UInt(FieldType):
    def __init__(self, bits: int):
        self.bits = bits

    def encode(self, out, value):
        if value is None:
            value = 0
        if value >> self.bits:
            raise ValueError(f"value {value} exceeds {self.bits} bits")
        out.write(encode_varint(int(value)))

    def decode(self, buf, pos):
        v, pos = decode_varint(buf, pos)
        if v >> self.bits:
            raise ValueError(f"decoded value {v} exceeds {self.bits} bits")
        return v, pos


U64 = _UInt(64)
U32 = _UInt(32)
I32 = _UInt(31)  # non-negative int32s only (matches all reference uses)


class _Bool(FieldType):
    def encode(self, out, value):
        out.write(b"\x01" if value else b"\x00")

    def decode(self, buf, pos):
        if pos >= len(buf):
            raise ValueError("truncated bool")
        b = buf[pos]
        if b > 1:
            raise ValueError("non-canonical bool")
        return bool(b), pos + 1


BOOL = _Bool()


class _Bytes(FieldType):
    def encode(self, out, value):
        if value is None:
            value = b""
        out.write(encode_varint(len(value)))
        out.write(value)

    def decode(self, buf, pos):
        n, pos = decode_varint(buf, pos)
        if pos + n > len(buf):
            raise ValueError("truncated bytes")
        return buf[pos : pos + n], pos + n


BYTES = _Bytes()


class Nested(FieldType):
    """Optional nested message (None allowed)."""

    def __init__(self, cls):
        self.cls = cls

    def encode(self, out, value):
        if value is None:
            out.write(b"\x00")
            return
        body = encode(value)
        out.write(b"\x01")
        out.write(encode_varint(len(body)))
        out.write(body)

    def decode(self, buf, pos):
        if pos >= len(buf):
            raise ValueError("truncated nested presence byte")
        present = buf[pos]
        pos += 1
        if present == 0:
            return None, pos
        if present != 1:
            raise ValueError("non-canonical presence byte")
        n, pos = decode_varint(buf, pos)
        if pos + n > len(buf):
            raise ValueError("truncated nested message")
        return decode(self.cls, buf[pos : pos + n]), pos + n


class Rep(FieldType):
    def __init__(self, item: FieldType):
        self.item = item

    def encode(self, out, value):
        if value is None:
            value = ()
        out.write(encode_varint(len(value)))
        for v in value:
            self.item.encode(out, v)

    def decode(self, buf, pos):
        n, pos = decode_varint(buf, pos)
        items = []
        for _ in range(n):
            v, pos = self.item.decode(buf, pos)
            items.append(v)
        return items, pos


class OneOf(FieldType):
    """Tagged union over message classes.  Value is an instance of one of the
    registered classes, or None (tag 0, only when ``allow_unset``).

    ``allow_unset=False`` makes tag 0 a decode error and None an encode
    error; used for oneofs where an empty value is never legitimate (wire
    messages, WAL entries, state events) so that malformed input is rejected
    at the codec boundary rather than deep inside the state machine.
    """

    def __init__(self, *entries: tuple[int, type], allow_unset: bool = True):
        self.allow_unset = allow_unset
        self.by_tag = {}
        self.by_cls = {}
        for tag, cls in entries:
            if tag <= 0:
                raise ValueError("oneof tags must be positive")
            if tag in self.by_tag or cls in self.by_cls:
                raise ValueError("duplicate oneof entry")
            self.by_tag[tag] = cls
            self.by_cls[cls] = tag

    def encode(self, out, value):
        if value is None:
            if not self.allow_unset:
                raise ValueError("oneof value must be set")
            out.write(b"\x00")
            return
        tag = self.by_cls.get(type(value))
        if tag is None:
            raise TypeError(
                f"{type(value).__name__} is not a member of this oneof"
            )
        body = encode(value)
        out.write(encode_varint(tag))
        out.write(encode_varint(len(body)))
        out.write(body)

    def decode(self, buf, pos):
        tag, pos = decode_varint(buf, pos)
        if tag == 0:
            if not self.allow_unset:
                raise ValueError("oneof value must be set")
            return None, pos
        cls = self.by_tag.get(tag)
        if cls is None:
            raise ValueError(f"unknown oneof tag {tag}")
        n, pos = decode_varint(buf, pos)
        if pos + n > len(buf):
            raise ValueError("truncated oneof body")
        return decode(cls, buf[pos : pos + n]), pos + n


def _spec_of(cls) -> tuple:
    spec = getattr(cls, "_spec_", None)
    if spec is None:
        raise TypeError(f"{cls.__name__} has no _spec_")
    return spec


def encode(msg) -> bytes:
    out = io.BytesIO()
    for name, ft in _spec_of(type(msg)):
        ft.encode(out, getattr(msg, name))
    return out.getvalue()


def decode(cls, buf: bytes):
    values = {}
    pos = 0
    for name, ft in _spec_of(cls):
        values[name], pos = ft.decode(buf, pos)
    if pos != len(buf):
        raise ValueError(f"{cls.__name__}: {len(buf) - pos} trailing bytes")
    return cls(**values)


def check_spec(cls) -> None:
    """Assert the _spec_ names exactly match the dataclass fields, in order."""
    spec_names = [n for n, _ in _spec_of(cls)]
    field_names = [f.name for f in dc_fields(cls)]
    if spec_names != field_names:
        raise TypeError(
            f"{cls.__name__}: spec fields {spec_names} != dataclass fields {field_names}"
        )
