"""The open-loop load generator.

``LoadGenerator`` drives any cluster object exposing the small duck
interface below, submits requests at the instants an arrival process
planned (never waiting for completions — open loop), tracks per-request
submit→commit latency against the cluster's own fsynced commit records,
and reduces each rate step to the latency/goodput summary the SLO gate
(slo.py) consumes.

Cluster duck interface (implemented by ``ClusterSupervisor`` and by the
in-process ``InProcessCluster`` used in tier-1 tests):

- ``node_ids`` — iterable of node ids accepting submissions
- ``submit(node_id, request)`` — fire-and-forget client submission
- ``poll_commits()`` — newly observed commits as
  ``(node_id, client_id, req_no, seq, ts_ns)`` tuples; ``ts_ns`` is the
  committing node's ``time.monotonic_ns()`` stamp (CLOCK_MONOTONIC is
  system-wide on one host, so subtraction against the generator's own
  clock is meaningful), or None when the backend does not stamp.

Latency is measured from the *first* submission of a request to the
first commit observation anywhere — the client-perceived number; a
retry-storm resubmission never resets the clock, and every resubmission
is counted as a duplicate rather than as goodput.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from .. import pb


def percentile_ms(latencies_ms: list, q: float) -> float:
    """Nearest-rank percentile; 0.0 for an empty sample."""
    if not latencies_ms:
        return 0.0
    ordered = sorted(latencies_ms)
    rank = max(1, -(-int(q * 100) * len(ordered) // 100))  # ceil
    return ordered[min(rank, len(ordered)) - 1]


@dataclass
class StepResult:
    """One arrival-rate step's measured outcome."""

    name: str
    offered_rate_per_sec: float
    duration_s: float
    submitted: int = 0
    duplicates: int = 0  # retry-storm resubmissions (never goodput)
    committed: int = 0
    timed_out: int = 0  # uncommitted when the drain window closed
    goodput_per_sec: float = 0.0
    p50_ms: float = 0.0
    p95_ms: float = 0.0
    p99_ms: float = 0.0
    latencies_ms: list = field(default_factory=list)
    # Per-request commit records for critical-path attribution
    # (obsv/critpath.py joins these to trace flow milestones by seq):
    # dicts {client_id, req_no, seq, node, submit_ns, commit_ns}.
    # Not part of the SLO artifact (slo.py enumerates its fields).
    records: list = field(default_factory=list)

    def finalize(self) -> None:
        self.goodput_per_sec = (
            self.committed / self.duration_s if self.duration_s > 0 else 0.0
        )
        self.p50_ms = percentile_ms(self.latencies_ms, 0.50)
        self.p95_ms = percentile_ms(self.latencies_ms, 0.95)
        self.p99_ms = percentile_ms(self.latencies_ms, 0.99)


class _Pending:
    __slots__ = ("client_id", "req_no", "data", "submit_ns", "last_send_s", "model")

    def __init__(self, client_id, req_no, data, submit_ns, last_send_s, model):
        self.client_id = client_id
        self.req_no = req_no
        self.data = data
        self.submit_ns = submit_ns
        self.last_send_s = last_send_s
        self.model = model


class LoadGenerator:
    """Open-loop traffic against one cluster, stepped by arrival rate."""

    def __init__(self, cluster, client_models: dict, seed: int = 0):
        if not client_models:
            raise ValueError("at least one client model is required")
        self.cluster = cluster
        self.client_models = dict(client_models)
        self.seed = seed
        self.node_ids = list(cluster.node_ids)
        # req_no counters persist across steps: the client window keeps
        # advancing, so later steps exercise watermark movement too.
        self._req_no = {client_id: 0 for client_id in self.client_models}
        self._rng = random.Random((seed << 1) ^ 0x85EBCA6B)
        # Lazy Ed25519 signer for ClientModel.signed traffic; built on
        # first use so unsigned runs never import the crypto stack.
        self._signer = None

    # -- one rate step -------------------------------------------------------

    def run_step(
        self,
        name: str,
        arrivals,
        duration_s: float,
        drain_s: float = 15.0,
    ) -> StepResult:
        """Submit the arrival plan open-loop over ``duration_s``, then
        drain up to ``drain_s`` more waiting for stragglers."""
        offsets = arrivals.offsets(duration_s)
        client_ids = sorted(self.client_models)
        plan = []  # (effective_offset_s, client_id, req_no, data, model)
        for i, offset in enumerate(offsets):
            client_id = client_ids[i % len(client_ids)]
            model = self.client_models[client_id]
            req_no = self._req_no[client_id]
            self._req_no[client_id] += 1
            data = model.payload(self._rng, req_no)
            if model.signed:
                # Sign at plan build (not send time): a retry re-submits
                # the same bytes, and signing off the paced path keeps
                # the open-loop schedule honest.
                if self._signer is None:
                    from ..testengine import signing

                    self._signer = signing.make_signer()
                data = self._signer(client_id, req_no, data)
            plan.append(
                (offset + model.submit_lag_s, client_id, req_no, data, model)
            )
        plan.sort(key=lambda item: item[0])

        result = StepResult(
            name=name,
            offered_rate_per_sec=getattr(
                arrivals, "rate_per_sec", len(offsets) / max(duration_s, 1e-9)
            ),
            duration_s=duration_s,
        )
        pending: dict = {}  # (client_id, req_no) -> _Pending
        start = time.monotonic()
        cursor = 0
        # Submission phase: wall-pace the plan; poll commits between sends.
        while cursor < len(plan):
            now_s = time.monotonic() - start
            due = plan[cursor][0]
            if now_s < due:
                self._observe(pending, result)
                self._retry(pending, result, start)
                time.sleep(min(due - now_s, 0.005))
                continue
            _off, client_id, req_no, data, model = plan[cursor]
            cursor += 1
            request = pb.Request(client_id=client_id, req_no=req_no, data=data)
            # The Mir-BFT client contract: broadcast to every node — a
            # weak quorum (f+1) must hold the request before its ack set
            # can form, so single-node submission never commits.
            for node_id in self.node_ids:
                self.cluster.submit(node_id, request)
            result.submitted += 1
            pending[(client_id, req_no)] = _Pending(
                client_id,
                req_no,
                data,
                time.monotonic_ns(),
                time.monotonic() - start,
                model,
            )
        # Drain phase: wait out stragglers (retries still fire).
        deadline = time.monotonic() + drain_s
        while pending and time.monotonic() < deadline:
            self._observe(pending, result)
            self._retry(pending, result, start)
            if pending:
                time.sleep(0.005)
        self._observe(pending, result)
        result.timed_out = len(pending)
        result.finalize()
        return result

    def _observe(self, pending: dict, result: StepResult) -> None:
        for node, client_id, req_no, seq, ts_ns in self.cluster.poll_commits():
            entry = pending.pop((client_id, req_no), None)
            if entry is None:
                continue  # another node's commit already scored it
            end_ns = ts_ns if ts_ns is not None else time.monotonic_ns()
            result.latencies_ms.append(
                max(0.0, (end_ns - entry.submit_ns) / 1e6)
            )
            result.committed += 1
            result.records.append(
                {
                    "client_id": client_id,
                    "req_no": req_no,
                    "seq": seq,
                    "node": node,
                    "submit_ns": entry.submit_ns,
                    "commit_ns": end_ns,
                }
            )

    def _retry(self, pending: dict, result: StepResult, start: float) -> None:
        now_s = time.monotonic() - start
        for entry in pending.values():
            timeout = entry.model.retry_timeout_s
            if timeout is None or now_s - entry.last_send_s < timeout:
                continue
            entry.last_send_s = now_s
            request = pb.Request(
                client_id=entry.client_id, req_no=entry.req_no, data=entry.data
            )
            # The storm: same request, several nodes at once.
            fanout = min(entry.model.retry_fanout, len(self.node_ids))
            first = self._rng.randrange(len(self.node_ids))
            for k in range(fanout):
                node_id = self.node_ids[(first + k) % len(self.node_ids)]
                self.cluster.submit(node_id, request)
                result.duplicates += 1
