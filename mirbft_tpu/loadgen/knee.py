"""The knee finder: max-sustainable-rate-at-SLO capacity search.

PBFT's evaluation warns that throughput collapses past saturation;
Mir-BFT's plots the same cliff at WAN scale.  This driver locates our
cliff — the *knee* — by stepping an arrival-rate measurement until the
latency SLO's p95 breaks, then binary-searching the break point.  The
measurement itself is injected (``measure(rate) -> StepResult``-duck),
so the search is unit-testable against synthetic latency/rate curves
and the bench rung supplies a real ``LoadGenerator.run_step`` closure.

The output is the ``mirbft-capacity/1`` artifact: per config
(lan/wan profile × serial/pipelined processor) the measured
rate→p50/p95/p99 curve, the knee rate, and — when the caller provides
it — the per-phase critical-path attribution at the knee
(obsv/critpath.py).  ``obsv --diff`` gates ``knee_rate_per_sec``
PR-over-PR exactly like a p95 regression: the series name carries the
``per_sec`` token, so a knee that moves down fails the diff.
"""

from __future__ import annotations

from dataclasses import dataclass, field

SCHEMA = "mirbft-capacity/1"


@dataclass
class KneeResult:
    """One config's capacity search outcome."""

    slo_p95_ms: float
    steps: list = field(default_factory=list)  # measurement dicts, in order
    knee_rate_per_sec: float | None = None  # highest rate meeting the SLO
    located: bool = False  # False: SLO never broke within the budget

    @property
    def max_measured_ok(self) -> float:
        """Highest rate that passed (0.0 if none did)."""
        return max(
            (s["rate_per_sec"] for s in self.steps if s["ok"]), default=0.0
        )


def _step_doc(rate, result, ok):
    return {
        "rate_per_sec": float(rate),
        "p50_ms": float(getattr(result, "p50_ms", 0.0)),
        "p95_ms": float(getattr(result, "p95_ms", 0.0)),
        "p99_ms": float(getattr(result, "p99_ms", 0.0)),
        "goodput_per_sec": float(getattr(result, "goodput_per_sec", 0.0)),
        "committed": int(getattr(result, "committed", 0)),
        "ok": bool(ok),
    }


def find_knee(
    measure,
    start_rate: float,
    slo_p95_ms: float,
    *,
    step_factor: float = 2.0,
    max_rate: float = float("inf"),
    max_steps: int = 12,
    resolution: float = 0.15,
    min_goodput_ratio: float = 0.0,
) -> KneeResult:
    """Locate the max sustainable rate whose measured p95 meets the SLO.

    Phase 1 ramps geometrically from ``start_rate`` by ``step_factor``
    until a measurement breaks the SLO (p95 above ``slo_p95_ms``, or
    nothing committed), ``max_rate`` is cleared, or ``max_steps``
    measurements are spent.  Phase 2 binary-searches between the last
    passing and first failing rates until the bracket is within
    ``resolution`` (relative) or the budget runs out; the knee is the
    highest passing rate.

    ``min_goodput_ratio`` additionally requires goodput to keep up with
    the offered rate: past hard saturation almost nothing commits, so
    the p95 of the few survivors is a tiny-sample lottery that can land
    under the SLO and read as a pass.  Requiring
    ``goodput >= ratio * rate`` makes the collapse fail the probe
    regardless of how the surviving sample's percentile falls.

    No knee within budget — the SLO never broke — returns
    ``located=False`` with ``knee_rate_per_sec=None``: the honest
    verdict, not a fabricated knee (the caller should widen
    ``max_rate`` or the step budget).  Symmetrically, if *no* probe
    ever passes (the SLO never held, even as the search descends toward
    zero), the result is also ``located=False``: a knee of 0.0 is not a
    capacity, it is a wedged or starved cluster, and it must not drag
    down the artifact's min-across-configs headline.
    """
    result = KneeResult(slo_p95_ms=slo_p95_ms)

    def probe(rate):
        step = measure(rate)
        ok = (
            getattr(step, "committed", 0) > 0
            and getattr(step, "p95_ms", float("inf")) <= slo_p95_ms
            and getattr(step, "goodput_per_sec", 0.0)
            >= min_goodput_ratio * rate
        )
        result.steps.append(_step_doc(rate, step, ok))
        return ok

    # Phase 1: geometric ramp to bracket the knee.
    rate = float(start_rate)
    last_pass = None
    first_fail = None
    while len(result.steps) < max_steps:
        if probe(rate):
            last_pass = rate
            next_rate = rate * step_factor
            if next_rate > max_rate:
                break
            rate = next_rate
        else:
            first_fail = rate
            break

    if first_fail is None:
        # SLO never broke: no knee within the rate/step budget.
        result.knee_rate_per_sec = None
        result.located = False
        return result

    # Phase 2: binary search inside (last_pass, first_fail).
    lo = last_pass if last_pass is not None else 0.0
    hi = first_fail
    while len(result.steps) < max_steps and (hi - lo) > resolution * hi:
        mid = (lo + hi) / 2.0
        if mid <= 0.0:
            break
        if probe(mid):
            lo = mid
        else:
            hi = mid
    if last_pass is None and lo == 0.0:
        # Every probe failed, including the binary search's descent
        # toward zero: the SLO never *held*, so there is no sustainable
        # rate to report.  Claiming a located knee of 0.0 would poison
        # the artifact's min-across-configs headline with a number that
        # reflects a wedged or starved cluster, not a capacity.
        result.knee_rate_per_sec = None
        result.located = False
        return result
    result.knee_rate_per_sec = lo
    result.located = True
    return result


def config_doc(
    name: str,
    result: KneeResult,
    *,
    profile: str | None = None,
    processor: str | None = None,
    attribution=None,
    **extra,
) -> dict:
    """One config's entry for the capacity artifact."""
    doc = {
        "config": name,
        "slo_p95_ms": result.slo_p95_ms,
        "located": result.located,
        "knee_rate_per_sec": result.knee_rate_per_sec,
        "steps": list(result.steps),
    }
    if profile is not None:
        doc["profile"] = profile
    if processor is not None:
        doc["processor"] = processor
    if attribution is not None:
        # obsv.critpath.attribute() output at the knee: which phase
        # dominated each latency band, and on which node.
        doc["attribution"] = attribution
    doc.update(extra)
    return doc


def artifact(configs: list, **meta) -> dict:
    """Assemble the ``mirbft-capacity/1`` artifact.

    The headline ``knee_rate_per_sec`` is the *minimum* located knee
    across configs — the cluster is only as fast as its slowest
    configuration, and the diff gate should catch any config's knee
    moving down even when the others hold.
    """
    located = [
        c["knee_rate_per_sec"]
        for c in configs
        if c.get("located") and c.get("knee_rate_per_sec") is not None
    ]
    doc = {
        "schema": SCHEMA,
        "configs": list(configs),
        "knee_rate_per_sec": min(located) if located else None,
    }
    if meta:
        doc["meta"] = dict(meta)
    return doc
