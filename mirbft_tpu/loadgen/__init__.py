"""Open-loop load generation with a latency-SLO gate.

- ``arrivals``: Poisson / bursty on-off / diurnal-ramp arrival plans
  (deterministic under a seed — the offered load is an input, not a
  measurement).
- ``clients``: client behaviour models — slow clients, mixed payload
  sizes, and retry storms that re-submit timed-out requests to several
  nodes (the hostile load request dedup exists for).
- ``generator``: ``LoadGenerator`` drives any cluster exposing
  ``node_ids`` / ``submit`` / ``poll_commits`` (the multi-process
  ``ClusterSupervisor`` or the tier-1 ``InProcessCluster``), tracking
  per-request submit→commit latency against the cluster's own commit
  records.
- ``slo``: the ``mirbft-loadgen-slo/1`` artifact + absolute SLO gate;
  ``obsv --diff`` consumes the artifact directly for the relative gate.
- ``inproc``: the no-sockets, no-fsync in-process backend for fast
  tests.
- ``kv``: ``KvWorkload`` drives the replicated KV service's own API
  (mixed reads/writes per ClientModel) and reports the user-visible
  read/write latency split (docs/APP.md).
- ``knee``: the capacity search — ramp + binary-search the max
  sustainable rate whose p95 meets the SLO, emitting the
  ``mirbft-capacity/1`` artifact the diff gate tracks PR-over-PR.
"""

from .arrivals import (  # noqa: F401
    BurstyArrivals,
    DiurnalArrivals,
    PoissonArrivals,
)
from .clients import (  # noqa: F401
    ClientModel,
    kv_client_models,
    standard_client_models,
)
from .generator import LoadGenerator, StepResult, percentile_ms  # noqa: F401
from .inproc import InProcessCluster  # noqa: F401
from .knee import KneeResult, find_knee  # noqa: F401
from .kv import KvStepResult, KvWorkload  # noqa: F401
from .slo import (  # noqa: F401
    SCHEMA,
    artifact,
    check_slo,
    load_artifact,
    write_artifact,
)
