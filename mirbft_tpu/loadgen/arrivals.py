"""Open-loop arrival processes.

An arrival process plans *when* requests enter the system, independent
of how fast the system absorbs them — the defining property of open-loop
load (closed-loop generators hide saturation by self-throttling; an
open-loop one exposes it as queueing delay, which is what a latency SLO
must observe).

Every process is deterministic under its seed: ``offsets(duration_s)``
returns the full sorted plan up front, so a run can be replayed and the
offered rate is an artifact input rather than a measurement.
"""

from __future__ import annotations

import math
import random


class PoissonArrivals:
    """Memoryless arrivals at a constant offered rate (exponential
    inter-arrival gaps) — the canonical open-loop reference load."""

    def __init__(self, rate_per_sec: float, seed: int = 0):
        if rate_per_sec <= 0:
            raise ValueError("rate_per_sec must be positive")
        self.rate_per_sec = rate_per_sec
        self.seed = seed

    def offsets(self, duration_s: float) -> list:
        rng = random.Random((self.seed << 1) ^ 0x9E3779B9)
        out = []
        t = rng.expovariate(self.rate_per_sec)
        while t < duration_s:
            out.append(t)
            t += rng.expovariate(self.rate_per_sec)
        return out


class BurstyArrivals:
    """On-off bursts: Poisson at ``rate_per_sec * burst_factor`` during
    ``on_s`` windows, silent during ``off_s`` windows.  The long-run
    average rate stays near ``rate_per_sec * burst_factor * duty`` —
    bursts probe queue buildup and drain, not steady state."""

    def __init__(
        self,
        rate_per_sec: float,
        burst_factor: float = 4.0,
        on_s: float = 0.5,
        off_s: float = 1.0,
        seed: int = 0,
    ):
        if rate_per_sec <= 0 or burst_factor <= 0:
            raise ValueError("rates must be positive")
        if on_s <= 0 or off_s < 0:
            raise ValueError("window lengths must be positive")
        self.rate_per_sec = rate_per_sec
        self.burst_factor = burst_factor
        self.on_s = on_s
        self.off_s = off_s
        self.seed = seed

    def offsets(self, duration_s: float) -> list:
        rng = random.Random((self.seed << 1) ^ 0xB5297A4D)
        burst_rate = self.rate_per_sec * self.burst_factor
        period = self.on_s + self.off_s
        out = []
        window_start = 0.0
        while window_start < duration_s:
            t = window_start + rng.expovariate(burst_rate)
            on_end = min(window_start + self.on_s, duration_s)
            while t < on_end:
                out.append(t)
                t += rng.expovariate(burst_rate)
            window_start += period
        return out


class DiurnalArrivals:
    """A smooth rate ramp between ``low`` and ``high`` over ``period_s``
    (one squashed "day"), realised by thinning a Poisson stream at the
    peak rate — arrival density follows the instantaneous rate exactly."""

    def __init__(
        self,
        low_rate_per_sec: float,
        high_rate_per_sec: float,
        period_s: float = 10.0,
        seed: int = 0,
    ):
        if low_rate_per_sec < 0 or high_rate_per_sec <= 0:
            raise ValueError("rates must be positive")
        if high_rate_per_sec < low_rate_per_sec:
            raise ValueError("high rate must be >= low rate")
        if period_s <= 0:
            raise ValueError("period_s must be positive")
        self.low = low_rate_per_sec
        self.high = high_rate_per_sec
        self.period_s = period_s
        self.seed = seed

    def rate_at(self, t: float) -> float:
        phase = (1.0 - math.cos(2.0 * math.pi * t / self.period_s)) / 2.0
        return self.low + (self.high - self.low) * phase

    def offsets(self, duration_s: float) -> list:
        rng = random.Random((self.seed << 1) ^ 0x1B873593)
        out = []
        t = rng.expovariate(self.high)
        while t < duration_s:
            if rng.random() * self.high < self.rate_at(t):
                out.append(t)
            t += rng.expovariate(self.high)
        return out
