"""The latency-SLO gate and its timeline-compatible artifact.

A load run reduces to a JSON artifact (schema ``mirbft-loadgen-slo/1``)
holding, per arrival-rate step, the offered rate, goodput, duplicate
count, and the p50/p95/p99 submit→commit latencies.  The artifact is a
first-class ``obsv --diff`` input: ``obsv.diff.extract_series`` flattens
it to ``step.<name>.<metric>`` series, the higher-/lower-is-better
direction rules already understand ``goodput_per_sec`` and ``*_ms``,
and the diff CLI exits nonzero on regression — the same gate the
timeline profiles use, pointed at latency SLOs.

``check_slo`` is the absolute gate (this artifact against fixed
bounds); ``obsv --diff`` is the relative gate (this artifact against a
baseline artifact).  bench.py's ``live_mp_*`` rung embeds the artifact
under the run payload's ``"loadgen"`` key so one bench JSON carries
both views.
"""

from __future__ import annotations

import json

SCHEMA = "mirbft-loadgen-slo/1"


# Per-step read/write latency split, present only when the step object
# carries it (the KV app rung's KvStepResult does; the raw-bytes
# generator's StepResult does not) — consumers must treat these keys as
# optional.
_RW_KEYS = (
    "reads",
    "reads_failed",
    "writes",
    "read_goodput_per_sec",
    "write_goodput_per_sec",
    "read_p50_ms",
    "read_p95_ms",
    "read_p99_ms",
    "write_p50_ms",
    "write_p95_ms",
    "write_p99_ms",
)


def artifact(steps: list, **meta) -> dict:
    """Assemble the SLO artifact from ``StepResult``s (or any objects
    with the same fields)."""
    docs = []
    for step in steps:
        entry = {
            "name": step.name,
            "offered_rate_per_sec": step.offered_rate_per_sec,
            "duration_s": step.duration_s,
            "submitted": step.submitted,
            "duplicates": step.duplicates,
            "committed": step.committed,
            "timed_out": step.timed_out,
            "goodput_per_sec": step.goodput_per_sec,
            "p50_ms": step.p50_ms,
            "p95_ms": step.p95_ms,
            "p99_ms": step.p99_ms,
        }
        for key in _RW_KEYS:
            value = getattr(step, key, None)
            if value is not None:
                entry[key] = value
        docs.append(entry)
    doc = {"schema": SCHEMA, "steps": docs}
    if meta:
        doc["meta"] = dict(meta)
    return doc


def write_artifact(path: str, doc: dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_artifact(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not str(doc.get("schema", "")).startswith("mirbft-loadgen-slo"):
        raise ValueError(f"{path} is not a loadgen SLO artifact")
    return doc


def check_slo(
    doc: dict,
    p95_ms: float | None = None,
    p99_ms: float | None = None,
    min_goodput_ratio: float = 0.0,
    max_timed_out: int = 0,
) -> list:
    """Absolute gate: every step must meet the latency bounds, commit at
    least ``min_goodput_ratio`` of its offered rate, and strand at most
    ``max_timed_out`` requests.  Returns violation strings (empty =
    pass)."""
    violations = []
    for step in doc["steps"]:
        name = step["name"]
        if p95_ms is not None and step["p95_ms"] > p95_ms:
            violations.append(
                f"{name}: p95 {step['p95_ms']:.1f}ms > SLO {p95_ms:.1f}ms"
            )
        if p99_ms is not None and step["p99_ms"] > p99_ms:
            violations.append(
                f"{name}: p99 {step['p99_ms']:.1f}ms > SLO {p99_ms:.1f}ms"
            )
        floor = step["offered_rate_per_sec"] * min_goodput_ratio
        if step["goodput_per_sec"] < floor:
            violations.append(
                f"{name}: goodput {step['goodput_per_sec']:.1f}/s below "
                f"{min_goodput_ratio:.0%} of offered "
                f"{step['offered_rate_per_sec']:.1f}/s"
            )
        if step["timed_out"] > max_timed_out:
            violations.append(
                f"{name}: {step['timed_out']} requests never committed "
                f"(allowed: {max_timed_out})"
            )
    return violations
