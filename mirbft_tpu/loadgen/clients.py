"""Client behaviour models for the load generator.

An arrival process says *when* a request enters; a ``ClientModel`` says
*how*: payload sizing (fixed or mixed), added submit lag (a slow client
whose requests reach the cluster late), and the retry-storm policy — a
client that re-submits a request it believes timed out, fanned out to
several nodes at once.  Retries are the hostile case request dedup
exists for (PAPER.md's duplicate-suppression claim); the generator
counts them separately so goodput never double-counts a retried commit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass


@dataclass(frozen=True)
class ClientModel:
    """How one client misbehaves (or doesn't)."""

    # Fixed payload size, or choose-per-request from payload_choices.
    payload_bytes: int = 32
    payload_choices: tuple = ()  # e.g. (16, 256, 4096) for mixed sizes
    # A slow client: its requests arrive this long after their planned
    # open-loop instant.
    submit_lag_s: float = 0.0
    # Retry storm: when a request is uncommitted for retry_timeout_s,
    # re-submit it to retry_fanout distinct nodes (round-robin over the
    # cluster); None disables retries.
    retry_timeout_s: float | None = None
    retry_fanout: int = 1
    # KV workload shape (consumed by the app-rung driver; the raw-bytes
    # generator ignores these).  read_ratio is the probability an op is a
    # read; key_space keys named k0..k{n-1}; key_dist picks which —
    # "uniform", or "zipf" with exponent zipf_s (rank-1 hottest).
    read_ratio: float = 0.0
    key_space: int = 64
    key_dist: str = "uniform"
    zipf_s: float = 1.1
    # Signed mode: the generator wraps every payload with the Ed25519
    # trailer (testengine/signing wire format: payload || sig || pk) at
    # plan-build time, so mp/live rungs drive real signed traffic
    # through the socket path and the replicas' speculative ingress
    # stage verifies it (docs/CRYPTO.md).  Signing at plan build keeps
    # retries byte-identical, which dedup requires.
    signed: bool = False

    def __post_init__(self):
        if self.payload_bytes <= 0:
            raise ValueError("payload_bytes must be positive")
        if self.submit_lag_s < 0:
            raise ValueError("submit_lag_s must be >= 0")
        if self.retry_timeout_s is not None and self.retry_timeout_s <= 0:
            raise ValueError("retry_timeout_s must be positive")
        if self.retry_fanout < 1:
            raise ValueError("retry_fanout must be >= 1")
        if not 0.0 <= self.read_ratio <= 1.0:
            raise ValueError("read_ratio must be in [0, 1]")
        if self.key_space < 1:
            raise ValueError("key_space must be >= 1")
        if self.key_dist not in ("uniform", "zipf"):
            raise ValueError("key_dist must be 'uniform' or 'zipf'")
        if self.zipf_s <= 0:
            raise ValueError("zipf_s must be positive")

    def is_read(self, rng: random.Random) -> bool:
        return self.read_ratio > 0.0 and rng.random() < self.read_ratio

    def key(self, rng: random.Random) -> str:
        """Draw a key per key_dist.  The zipf draw is the standard
        inverse-CDF over harmonic weights, precomputed once per model."""
        if self.key_dist == "uniform" or self.key_space == 1:
            return f"k{rng.randrange(self.key_space)}"
        cdf = _zipf_cdf(self.key_space, self.zipf_s)
        point = rng.random()
        lo, hi = 0, len(cdf) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if cdf[mid] < point:
                lo = mid + 1
            else:
                hi = mid
        return f"k{lo}"

    def payload(self, rng: random.Random, req_no: int) -> bytes:
        size = (
            rng.choice(self.payload_choices)
            if self.payload_choices
            else self.payload_bytes
        )
        # Stamp the req_no, pad deterministically: payloads differ per
        # request but replays of the same (client, req_no) are identical,
        # which dedup requires.
        stamp = b"%d:" % req_no
        return (stamp + b"x" * size)[: max(size, len(stamp))]


_ZIPF_CDFS: dict = {}


def _zipf_cdf(n: int, s: float) -> list:
    cdf = _ZIPF_CDFS.get((n, s))
    if cdf is None:
        weights = [1.0 / (rank**s) for rank in range(1, n + 1)]
        total = sum(weights)
        acc = 0.0
        cdf = []
        for w in weights:
            acc += w / total
            cdf.append(acc)
        cdf[-1] = 1.0
        _ZIPF_CDFS[(n, s)] = cdf
    return cdf


# The mix exercised by the bench rung: one honest client, one slow
# client with mixed payload sizes, one retry-stormer.
def standard_client_models(client_ids) -> dict:
    """Assign models round-robin over ``(honest, slow+mixed, stormy)``."""
    models = (
        ClientModel(),
        ClientModel(payload_choices=(16, 256, 1024), submit_lag_s=0.05),
        ClientModel(retry_timeout_s=1.0, retry_fanout=2),
    )
    return {
        client_id: models[i % len(models)]
        for i, client_id in enumerate(client_ids)
    }


def kv_client_models(client_ids, read_ratio: float = 0.5) -> dict:
    """The app-rung mix: every client reads and writes; payload sizes
    alternate between small-value and mixed, key distributions between
    uniform and a Zipf hot set (the skew PAPER.md's bucket rotation is
    supposed to absorb)."""
    models = (
        ClientModel(read_ratio=read_ratio, key_space=64),
        ClientModel(
            read_ratio=read_ratio,
            key_space=64,
            key_dist="zipf",
            payload_choices=(16, 256, 1024),
        ),
    )
    return {
        client_id: models[i % len(models)]
        for i, client_id in enumerate(client_ids)
    }
