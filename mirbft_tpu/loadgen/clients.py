"""Client behaviour models for the load generator.

An arrival process says *when* a request enters; a ``ClientModel`` says
*how*: payload sizing (fixed or mixed), added submit lag (a slow client
whose requests reach the cluster late), and the retry-storm policy — a
client that re-submits a request it believes timed out, fanned out to
several nodes at once.  Retries are the hostile case request dedup
exists for (PAPER.md's duplicate-suppression claim); the generator
counts them separately so goodput never double-counts a retried commit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass


@dataclass(frozen=True)
class ClientModel:
    """How one client misbehaves (or doesn't)."""

    # Fixed payload size, or choose-per-request from payload_choices.
    payload_bytes: int = 32
    payload_choices: tuple = ()  # e.g. (16, 256, 4096) for mixed sizes
    # A slow client: its requests arrive this long after their planned
    # open-loop instant.
    submit_lag_s: float = 0.0
    # Retry storm: when a request is uncommitted for retry_timeout_s,
    # re-submit it to retry_fanout distinct nodes (round-robin over the
    # cluster); None disables retries.
    retry_timeout_s: float | None = None
    retry_fanout: int = 1

    def __post_init__(self):
        if self.payload_bytes <= 0:
            raise ValueError("payload_bytes must be positive")
        if self.submit_lag_s < 0:
            raise ValueError("submit_lag_s must be >= 0")
        if self.retry_timeout_s is not None and self.retry_timeout_s <= 0:
            raise ValueError("retry_timeout_s must be positive")
        if self.retry_fanout < 1:
            raise ValueError("retry_fanout must be >= 1")

    def payload(self, rng: random.Random, req_no: int) -> bytes:
        size = (
            rng.choice(self.payload_choices)
            if self.payload_choices
            else self.payload_bytes
        )
        # Stamp the req_no, pad deterministically: payloads differ per
        # request but replays of the same (client, req_no) are identical,
        # which dedup requires.
        stamp = b"%d:" % req_no
        return (stamp + b"x" * size)[: max(size, len(stamp))]


# The mix exercised by the bench rung: one honest client, one slow
# client with mixed payload sizes, one retry-stormer.
def standard_client_models(client_ids) -> dict:
    """Assign models round-robin over ``(honest, slow+mixed, stormy)``."""
    models = (
        ClientModel(),
        ClientModel(payload_choices=(16, 256, 1024), submit_lag_s=0.05),
        ClientModel(retry_timeout_s=1.0, retry_fanout=2),
    )
    return {
        client_id: models[i % len(models)]
        for i, client_id in enumerate(client_ids)
    }
