"""KV workload driver: user-visible read/write SLOs over the app layer.

Where ``generator.LoadGenerator`` measures raw submit→commit latency of
opaque payloads, this driver speaks the replicated KV service's own
API — puts/gets/cas through ``KvSession`` (in-process) or ``KvClient``
(socket service) — so the measured latencies include the full
user-visible path: write = propose → consensus → apply → waiter wakeup;
committed read = read-index barrier wait + local state read.

Each session is driven by one worker thread (closed loop per session,
open fan across sessions); per-op read/write choice, key draw, and
payload size come from the session's ``ClientModel``.  Results reduce
to ``KvStepResult`` — a superset of the raw generator's ``StepResult``
— so ``slo.artifact`` emits the read/write latency split and
``obsv --diff`` gates it with the existing ``*_ms`` direction rules.

The driver also records an op history (invocation/response intervals
with observed versions) for ``chaos.invariants.check_linearizable_reads``.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from .generator import percentile_ms


@dataclass
class KvStepResult:
    """One KV workload step's measured outcome (StepResult superset)."""

    name: str
    offered_rate_per_sec: float
    duration_s: float
    submitted: int = 0
    duplicates: int = 0
    committed: int = 0
    timed_out: int = 0
    goodput_per_sec: float = 0.0
    p50_ms: float = 0.0
    p95_ms: float = 0.0
    p99_ms: float = 0.0
    latencies_ms: list = field(default_factory=list)
    # Read/write split (consumed by slo.artifact via _RW_KEYS).
    reads: int = 0
    reads_failed: int = 0
    writes: int = 0
    read_goodput_per_sec: float = 0.0
    write_goodput_per_sec: float = 0.0
    read_p50_ms: float = 0.0
    read_p95_ms: float = 0.0
    read_p99_ms: float = 0.0
    write_p50_ms: float = 0.0
    write_p95_ms: float = 0.0
    write_p99_ms: float = 0.0
    read_latencies_ms: list = field(default_factory=list)
    write_latencies_ms: list = field(default_factory=list)

    def finalize(self) -> None:
        if self.duration_s > 0:
            # committed counts every successful op (reads and writes);
            # the split goodputs are derived from the split tallies.
            self.goodput_per_sec = self.committed / self.duration_s
            reads_ok = self.reads - self.reads_failed
            self.read_goodput_per_sec = reads_ok / self.duration_s
            writes_ok = self.committed - reads_ok
            self.write_goodput_per_sec = max(writes_ok, 0) / self.duration_s
        self.p50_ms = percentile_ms(self.latencies_ms, 0.50)
        self.p95_ms = percentile_ms(self.latencies_ms, 0.95)
        self.p99_ms = percentile_ms(self.latencies_ms, 0.99)
        self.read_p50_ms = percentile_ms(self.read_latencies_ms, 0.50)
        self.read_p95_ms = percentile_ms(self.read_latencies_ms, 0.95)
        self.read_p99_ms = percentile_ms(self.read_latencies_ms, 0.99)
        self.write_p50_ms = percentile_ms(self.write_latencies_ms, 0.50)
        self.write_p95_ms = percentile_ms(self.write_latencies_ms, 0.95)
        self.write_p99_ms = percentile_ms(self.write_latencies_ms, 0.99)


class KvWorkload:
    """Drive KV sessions with model-shaped mixed read/write traffic."""

    def __init__(self, sessions: dict, client_models: dict, seed: int = 0):
        """``sessions``: client_id -> session (KvSession/KvClient duck:
        ``put(key, value, timeout=...)`` and ``get(key, mode=...,
        timeout=...)``).  ``client_models``: client_id -> ClientModel."""
        if not sessions:
            raise ValueError("at least one session is required")
        self.sessions = dict(sessions)
        self.client_models = dict(client_models)
        self.seed = seed
        self._payload_no = 0
        # Op history for the linearizability checker: list of dicts with
        # op/key/invoke_ns/return_ns/outcome and (for reads) the observed
        # (value, version); (for writes) the assigned version.
        self.history: list = []
        self._history_lock = threading.Lock()

    def _record(self, entry: dict) -> None:
        with self._history_lock:
            self.history.append(entry)

    def run_step(
        self,
        name: str,
        ops_per_session: int,
        op_timeout_s: float = 10.0,
    ) -> KvStepResult:
        """Each session issues ``ops_per_session`` ops closed-loop on its
        own thread; the step lasts as long as the slowest session."""
        lock = threading.Lock()
        tallies = {
            "submitted": 0,
            "committed": 0,
            "timed_out": 0,
            "reads": 0,
            "reads_failed": 0,
            "writes": 0,
            "lat": [],
            "read_lat": [],
            "write_lat": [],
        }

        def drive(client_id, session):
            rng = random.Random(
                (self.seed << 8) ^ (client_id * 0x9E3779B1) ^ 0x7F4A7C15
            )
            model = self.client_models[client_id]
            lat, read_lat, write_lat = [], [], []
            submitted = committed = timed_out = 0
            reads = reads_failed = writes = 0
            for op_no in range(ops_per_session):
                key = model.key(rng)
                is_read = model.is_read(rng)
                t0 = time.monotonic_ns()
                if is_read:
                    resp = session.get(key, timeout=op_timeout_s)
                else:
                    value = model.payload(rng, op_no)
                    resp = session.put(key, value, timeout=op_timeout_s)
                t1 = time.monotonic_ns()
                ms = (t1 - t0) / 1e6
                status = resp.get("status")
                submitted += 1
                entry = {
                    "client_id": client_id,
                    "op": "get" if is_read else "put",
                    "key": key,
                    "invoke_ns": t0,
                    "return_ns": t1,
                    "outcome": status,
                    "version": resp.get("version", 0),
                }
                if is_read:
                    reads += 1
                    if status in ("ok", "not_found"):
                        committed += 1
                        lat.append(ms)
                        read_lat.append(ms)
                        if status == "ok":
                            entry["value"] = resp.get("value")
                    else:
                        reads_failed += 1
                else:
                    writes += 1
                    entry["value"] = value.hex()
                    if status in ("ok", "not_found", "cas_conflict"):
                        committed += 1
                        lat.append(ms)
                        write_lat.append(ms)
                    else:
                        timed_out += 1
                self._record(entry)
            with lock:
                tallies["submitted"] += submitted
                tallies["committed"] += committed
                tallies["timed_out"] += timed_out
                tallies["reads"] += reads
                tallies["reads_failed"] += reads_failed
                tallies["writes"] += writes
                tallies["lat"].extend(lat)
                tallies["read_lat"].extend(read_lat)
                tallies["write_lat"].extend(write_lat)

        start = time.monotonic()
        threads = [
            threading.Thread(
                target=drive,
                args=(client_id, session),
                name=f"kv-loadgen-{client_id}",
                daemon=True,
            )
            for client_id, session in sorted(self.sessions.items())
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        duration_s = max(time.monotonic() - start, 1e-9)

        result = KvStepResult(
            name=name,
            offered_rate_per_sec=tallies["submitted"] / duration_s,
            duration_s=duration_s,
            submitted=tallies["submitted"],
            committed=tallies["committed"],
            timed_out=tallies["timed_out"],
            reads=tallies["reads"],
            reads_failed=tallies["reads_failed"],
            writes=tallies["writes"],
            latencies_ms=tallies["lat"],
            read_latencies_ms=tallies["read_lat"],
            write_latencies_ms=tallies["write_lat"],
        )
        result.finalize()
        return result
