"""An in-process cluster backend for the load generator.

``InProcessCluster`` satisfies the generator's duck interface with N
real runtime ``Node``s in one process: direct-call links (no sockets),
in-memory WAL/request-store stubs honouring the storage contract, and a
hash-chain app log that stamps each commit with ``time.monotonic_ns()``.
It exists so the tier-1 loadgen smoke test exercises the full
submit→consensus→commit→latency pipeline in a couple of seconds,
without process spawns or fsyncs; the multi-process path through
``ClusterSupervisor`` is covered by the slow-marked cluster tests and
the bench ``live_mp_*`` rung.

The consumer loop per node is the standard runtime embedding (see
``chaos.live.LiveReplica._consume``): ready → process → add_results,
with wall-clock ticks and the real TransferEngine (over a direct
in-process duct, memory-only staging) serving state transfer.
"""

from __future__ import annotations

import hashlib
import threading
import time

from .. import pb
from ..app import KvFrontend, KvStore
from ..app import kvstore as kv_ops
from ..app.stream import CommitStream
from ..runtime import Config, Node, build_processor
from ..runtime.node import NodeStopped, standard_initial_network_state
from ..runtime.processor import Link, Log
from ..runtime.reconfig import checkpoint_network_state
from ..runtime.transfer import TransferEngine


class MemWal:
    """The WAL storage contract, in memory (sync points are no-ops)."""

    def __init__(self):
        self.entries: dict = {}  # index -> encoded entry
        self.fault_hook = None

    def write(self, index: int, entry) -> None:
        self.entries[index] = entry

    def truncate(self, index: int) -> None:
        for stale in [i for i in self.entries if i < index]:
            del self.entries[stale]

    def sync(self) -> None:
        if self.fault_hook is not None:
            self.fault_hook()

    def sync_token(self) -> int:
        return 0

    def wait(self, token: int) -> None:
        pass

    def load_all(self, for_each) -> None:
        for index in sorted(self.entries):
            for_each(index, self.entries[index])

    def close(self) -> None:
        pass

    crash = close


class MemRequestStore:
    """The request-store contract, in memory."""

    def __init__(self):
        self.data: dict = {}  # (client_id, req_no, digest) -> payload
        self.committed: set = set()
        self.fault_hook = None

    @staticmethod
    def _key(ack) -> tuple:
        return (ack.client_id, ack.req_no, bytes(ack.digest))

    def store(self, ack, data: bytes) -> None:
        self.data[self._key(ack)] = data

    def get(self, ack):
        return self.data.get(self._key(ack))

    def commit(self, ack) -> None:
        self.committed.add(self._key(ack))

    def sync(self) -> None:
        if self.fault_hook is not None:
            self.fault_hook()

    def sync_token(self) -> int:
        return 0

    def wait(self, token: int) -> None:
        pass

    def uncommitted(self, for_each) -> None:
        for key, data in self.data.items():
            if key not in self.committed:
                client_id, req_no, digest = key
                for_each(
                    pb.RequestAck(
                        client_id=client_id, req_no=req_no, digest=digest
                    ),
                    data,
                )

    def close(self) -> None:
        pass

    crash = close


class MemChainLog(Log):
    """Hash-chain application state with monotonic commit stamps."""

    def __init__(self, node_id: int, sink):
        self.node_id = node_id
        self.sink = sink  # callable(node_id, client_id, req_no, seq, ts_ns)
        self.chain = b""
        self.commits: list = []  # [(client_id, req_no, seq)]
        self.last_seq = 0

    def apply(self, q_entry: pb.QEntry) -> None:
        if q_entry.seq_no <= self.last_seq:
            return
        ts_ns = time.monotonic_ns()
        for ack in q_entry.requests:
            h = hashlib.sha256()
            h.update(self.chain)
            h.update(ack.digest)
            self.chain = h.digest()
            self.commits.append((ack.client_id, ack.req_no, q_entry.seq_no))
            self.sink(
                self.node_id, ack.client_id, ack.req_no, q_entry.seq_no, ts_ns
            )
        self.last_seq = q_entry.seq_no

    def adopt(self, value: bytes, seq_no: int) -> None:
        self.chain = value
        if seq_no > self.last_seq:
            self.last_seq = seq_no

    def snap(self, network_config, clients_state) -> bytes:
        return self.chain


class _MemAppLog(Log):
    """KV mode: the chain log (commit stamps for the generator) composed
    with the commit stream — the in-process analogue of ``app.AppLog``
    without the durable journal."""

    def __init__(self, chain_log: MemChainLog, stream: CommitStream):
        self.chain_log = chain_log
        self.stream = stream
        stream.chain_source = lambda: chain_log.chain

    @property
    def chain(self) -> bytes:
        return self.chain_log.chain

    def apply(self, q_entry: pb.QEntry) -> None:
        self.chain_log.apply(q_entry)
        self.stream.apply(q_entry)

    def snap(self, network_config, clients_state) -> bytes:
        self.chain_log.snap(network_config, clients_state)
        return self.stream.snap(network_config, clients_state)

    def install(self, app_bytes: bytes, value: bytes, seq_no: int) -> bool:
        chain = CommitStream.chain_of(app_bytes)
        if chain is None or not self.stream.install(app_bytes, value, seq_no):
            return False
        self.chain_log.adopt(chain, seq_no)
        return True


class KvSession:
    """An in-process KV session over the frontends: the loopback
    equivalent of ``app.service.KvClient`` (same write broadcast and
    read-barrier semantics, direct calls instead of sockets)."""

    def __init__(self, cluster: "InProcessCluster", client_id: int,
                 home: int = 0):
        self.cluster = cluster
        self.client_id = client_id
        self.home = home
        self.req_no = 0
        self.session_index = 0

    def _observe(self, resp: dict) -> dict:
        for field in ("index", "version", "frontier"):
            val = resp.get(field)
            if isinstance(val, int) and val > self.session_index:
                self.session_index = val
        return resp

    def _write(self, data: bytes, timeout: float) -> dict:
        # Client windows open at req_no 0 and advance in order.
        req_no = self.req_no
        self.req_no += 1
        stream = self.cluster.replicas[self.home].stream
        waiter = stream.register_waiter(self.client_id, req_no)
        request = pb.Request(
            client_id=self.client_id, req_no=req_no, data=data
        )
        # The Mir client contract: broadcast the write to every node.
        for node_id in self.cluster.node_ids:
            self.cluster.submit(node_id, request)
        got = waiter.wait(timeout)
        if got is None:
            stream.cancel_waiter(self.client_id, req_no)
            return {"status": "timeout"}
        index, result = got
        return self._observe(
            {
                "status": (result or {}).get("outcome", "ok"),
                "version": (result or {}).get("version", index),
                "index": index,
            }
        )

    def put(self, key: str, value: bytes, timeout: float = 10.0) -> dict:
        return self._write(kv_ops.encode_put(key, value), timeout)

    def delete(self, key: str, timeout: float = 10.0) -> dict:
        return self._write(kv_ops.encode_delete(key), timeout)

    def cas(self, key: str, expect_version: int, value: bytes,
            timeout: float = 10.0) -> dict:
        return self._write(
            kv_ops.encode_cas(key, expect_version, value), timeout
        )

    def get(self, key: str, mode: str = "committed",
            timeout: float = 10.0) -> dict:
        frontend = self.cluster.replicas[self.home].frontend
        resp = frontend.execute(
            {
                "op": "get",
                "key": key,
                "mode": mode,
                "min_index": self.session_index if mode == "committed" else 0,
                "timeout": timeout,
            }
        )
        if resp.get("status") in ("ok", "not_found"):
            self._observe(resp)
        return resp


class _DirectLink(Link):
    """Same-process message passing: send == dest.step(source, msg)."""

    def __init__(self, cluster, source: int):
        self.cluster = cluster
        self.source = source

    def send(self, dest: int, msg: pb.Msg) -> None:
        replica = self.cluster.replicas[dest]
        if replica is None:
            return
        try:
            replica.node.step(self.source, msg)
        except (NodeStopped, ValueError):
            pass


class _DirectDuct:
    """Same-process transfer duct: send == dest engine's on_frame."""

    def __init__(self, cluster, source: int):
        self.cluster = cluster
        self.source = source

    def send(self, dest: int, body: bytes) -> None:
        replica = self.cluster.replicas[dest]
        if replica is None:
            return
        replica.engine.on_frame(self.source, body)


class _InProcReplica:
    def __init__(self, cluster, node_id: int, initial_state, processor: str):
        self.cluster = cluster
        self.node_id = node_id
        self.app_log = MemChainLog(node_id, cluster._on_commit)
        self.wal = MemWal()
        self.reqstore = MemRequestStore()
        config = Config(
            id=node_id,
            batch_size=cluster.batch_size,
            processor=processor,
        )
        self.node = Node.start_new(config, initial_state)
        self.stream = None
        self.frontend = None
        if cluster.app == "kv":
            self.store = KvStore()
            self.stream = self.node.attach_app(
                self.store,
                queue_depth=cluster.app_queue_depth,
                data_source=self.reqstore.get,
            )
            self.app_log = _MemAppLog(self.app_log, self.stream)
            self.frontend = KvFrontend(
                self.stream, self.store, self.node.propose
            )
        self.processor = build_processor(
            self.node,
            _DirectLink(cluster, node_id),
            self.app_log,
            self.wal,
            self.reqstore,
        )
        self.checkpoints: dict = {}
        if hasattr(self.processor, "on_results"):
            self.processor.on_results = self._capture_checkpoints
        self.engine = TransferEngine(
            node_id,
            _DirectDuct(cluster, node_id),
            staging_dir=None,  # memory-only embedder: no crash resume
            peers=list(initial_state.config.nodes),
            limits=config,
            install=self._install_snapshot,
            complete=self.node.state_transfer_complete,
            failed=self.node.state_transfer_failed,
            chunk_timeout_s=0.25,
        )
        self.failed = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._consume,
            name=f"loadgen-consumer-{node_id}",
            daemon=True,
        )

    def _capture_checkpoints(self, results) -> None:
        for cr in results.checkpoints:
            network_state = checkpoint_network_state(cr)
            self.checkpoints[cr.checkpoint.seq_no] = (cr.value, network_state)
            requests: list = []
            self.reqstore.uncommitted(
                lambda ack, data: requests.append((ack, data))
            )
            if self.stream is not None:
                app_bytes = (
                    self.stream.snapshot_blob(cr.value)
                    or self.stream.last_snapshot_blob
                    or b""
                )
            else:
                app_bytes = self.app_log.chain
            self.engine.note_checkpoint(
                cr.checkpoint.seq_no,
                cr.value,
                network_state,
                app_bytes,
                requests,
            )

    def _install_snapshot(self, snap):
        """TransferEngine install callback: adopt the app state (in KV
        mode the verified full state blob) and the donor's
        uncommitted-request slice, then let the node persist the
        checkpoint CEntry."""
        if self.stream is not None:
            if not self.app_log.install(
                snap.app_bytes, snap.value, snap.seq_no
            ):
                return None
        else:
            self.app_log.adopt(snap.value, snap.seq_no)
        for ack, data in snap.requests:
            self.reqstore.store(ack, data)
        return snap.network_state

    def _consume(self) -> None:
        tick_seconds = self.cluster.tick_seconds
        last_tick = time.monotonic()
        try:
            while not self._stop.is_set():
                actions = self.node.ready(timeout=0.01)
                if actions is not None:
                    results = self.processor.process(actions)
                    self._capture_checkpoints(results)
                    if results.digests or results.checkpoints:
                        self.node.add_results(results)
                now = time.monotonic()
                if now - last_tick >= tick_seconds:
                    last_tick = now
                    self.node.tick()
                if actions is not None and actions.state_transfer is not None:
                    self.engine.begin(actions.state_transfer)
                self.engine.poll()
        except NodeStopped:
            pass
        except Exception as err:  # noqa: BLE001 — surfaced via cluster.check()
            self.failed = err

    def stop(self) -> None:
        self._stop.set()
        closer = getattr(self.processor, "close", None)
        if closer is not None:
            try:
                closer()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        if self._thread.ident is not None:
            self._thread.join(timeout=10)
        self.node.stop()


class InProcessCluster:
    """N runtime nodes behind the load generator's duck interface."""

    def __init__(
        self,
        node_count: int = 4,
        client_ids=None,
        *,
        batch_size: int = 1,
        processor: str = "serial",
        tick_seconds: float = 0.02,
        app: str | None = None,
        app_queue_depth: int = 256,
    ):
        self.batch_size = batch_size
        self.tick_seconds = tick_seconds
        self.app = app
        self.app_queue_depth = app_queue_depth
        self.client_ids = list(client_ids) if client_ids else [1, 2]
        self._lock = threading.Lock()
        self._commits: list = []
        state = standard_initial_network_state(node_count, self.client_ids)
        self.replicas = [
            _InProcReplica(self, n, state, processor)
            for n in range(node_count)
        ]
        for replica in self.replicas:
            replica._thread.start()

    @property
    def node_ids(self) -> list:
        return [replica.node_id for replica in self.replicas]

    def _on_commit(self, node_id, client_id, req_no, seq, ts_ns) -> None:
        with self._lock:
            self._commits.append((node_id, client_id, req_no, seq, ts_ns))

    def submit(self, node_id: int, request: pb.Request) -> None:
        try:
            self.replicas[node_id].node.propose(request)
        except (NodeStopped, ValueError):
            pass

    def poll_commits(self) -> list:
        with self._lock:
            out = self._commits
            self._commits = []
        return out

    def kv_session(self, client_id: int, home: int = 0) -> KvSession:
        """A KV session over the in-process frontends (requires
        ``app="kv"``); ``client_id`` must be a registered client id."""
        if self.app != "kv":
            raise RuntimeError("kv_session requires InProcessCluster(app='kv')")
        return KvSession(self, client_id, home)

    def check(self) -> None:
        """Raise the first consumer/serializer failure, if any."""
        for replica in self.replicas:
            if replica.failed is not None:
                raise replica.failed
            if replica.node.exit_error is not None:
                raise replica.node.exit_error

    def close(self) -> None:
        for replica in self.replicas:
            replica.stop()

    def __enter__(self) -> "InProcessCluster":
        return self

    def __exit__(self, *_exc) -> None:
        self.close()
