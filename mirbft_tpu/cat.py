"""mirbft_tpu.cat — the recorded-log inspection / replay CLI.

Rebuild of the reference's mircat tool (reference: mircat/main.go:419-563,
mircat/textmarshal.go): filter a recorded event log by node / event type /
message type / index range, print each event in a truncated text form,
replay the log against fresh StateMachines to any index and print the
status snapshot there, report per-node event counts, and diff two logs to
their first divergence.

Usage:
  python -m mirbft_tpu.cat run.gz
  python -m mirbft_tpu.cat run.gz --node 0 --node 2 --event-type EventStep
  python -m mirbft_tpu.cat run.gz --msg-type Preprepare --from-index 100 --to-index 200
  python -m mirbft_tpu.cat run.gz --status-at 500 --pretty
  python -m mirbft_tpu.cat --diff a.gz b.gz
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

from . import pb
from .eventlog import Player, first_divergence, read_log


# ---------------------------------------------------------------------------
# Truncating text marshal (reference: mircat/textmarshal.go:22-33)
# ---------------------------------------------------------------------------

_MAX_BYTES_SHOWN = 4


def text(value, max_bytes: int = _MAX_BYTES_SHOWN) -> str:
    """Render a pb message compactly, truncating byte fields."""
    if value is None:
        return "-"
    if isinstance(value, bytes):
        if len(value) <= max_bytes:
            return value.hex() or "''"
        return f"{value[:max_bytes].hex()}…({len(value)}B)"
    if isinstance(value, (int, str, bool)):
        return str(value)
    if isinstance(value, (list, tuple)):
        if len(value) > 3:
            inner = ", ".join(text(v, max_bytes) for v in value[:3])
            return f"[{inner}, …{len(value)} total]"
        return "[" + ", ".join(text(v, max_bytes) for v in value) + "]"
    if dataclasses.is_dataclass(value):
        fields = []
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            if v in (None, b"", 0, [], False) and f.name != "type":
                continue
            fields.append(f"{f.name}={text(v, max_bytes)}")
        name = type(value).__name__
        return f"{name}{{{', '.join(fields)}}}"
    return repr(value)


def event_kind(event: pb.StateEvent) -> str:
    return type(event.type).__name__


def msg_kinds(event: pb.StateEvent) -> set:
    """Wire-message kinds carried by the event (a coalesced EventStepBatch
    can carry several; a --msg-type filter matches if any inner msg does)."""
    inner = event.type
    if isinstance(inner, pb.EventStep) and inner.msg is not None:
        return {type(inner.msg.type).__name__}
    if isinstance(inner, pb.EventStepBatch):
        return {type(m.type).__name__ for m in inner.msgs}
    return set()


# ---------------------------------------------------------------------------
# Filtering / commands
# ---------------------------------------------------------------------------


def filter_events(events, args):
    for index, recorded in enumerate(events):
        if args.from_index is not None and index < args.from_index:
            continue
        if args.to_index is not None and index > args.to_index:
            continue
        if args.node and recorded.node_id not in args.node:
            continue
        if args.event_type and event_kind(recorded.state_event) not in args.event_type:
            continue
        if args.msg_type:
            kinds = msg_kinds(recorded.state_event)
            if not kinds or kinds.isdisjoint(args.msg_type):
                continue
        yield index, recorded


def cmd_list(events, args, out) -> None:
    shown = 0
    for index, recorded in filter_events(events, args):
        line = (
            f"[{index:6d}] t={recorded.time_ms:<8d} node={recorded.node_id} "
            f"{text(recorded.state_event.type)}"
        )
        print(line, file=out)
        shown += 1
    print(f"# {shown}/{len(events)} events shown", file=out)


def cmd_summary(events, out) -> None:
    per_node: dict[int, int] = {}
    per_kind: dict[str, int] = {}
    for recorded in events:
        per_node[recorded.node_id] = per_node.get(recorded.node_id, 0) + 1
        kind = event_kind(recorded.state_event)
        per_kind[kind] = per_kind.get(kind, 0) + 1
    print(f"# events: {len(events)}", file=out)
    for node in sorted(per_node):
        print(f"# node {node}: {per_node[node]} events", file=out)
    for kind in sorted(per_kind):
        print(f"# {kind}: {per_kind[kind]}", file=out)


def cmd_status(events, args, out) -> None:
    from .status import state_machine_status

    player = Player(events)
    upto = args.status_at if args.status_at >= 0 else len(events)
    player.play(upto=upto)
    for node_id in sorted(player.nodes):
        machine = player.nodes[node_id].machine
        print(f"=== node {node_id} @ event {player.position} ===", file=out)
        try:
            status = state_machine_status(machine)
        except Exception as err:  # machine may be mid-bootstrap at this index
            print(f"(status unavailable: {err})", file=out)
            continue
        print(status.pretty() if args.pretty else status.to_json(), file=out)


def render_actions(actions, out, max_bytes: int = 16) -> None:
    """Textual rendering of one event's emitted Actions (the reference CLI
    prints aggregated actions during replay, mircat/main.go:419-503)."""
    if actions.is_empty():
        print("  (no actions)", file=out)
        return
    for send in actions.sends:
        print(
            f"  send {list(send.targets)}: {text(send.msg.type, max_bytes)}",
            file=out,
        )
    for fwd in actions.forward_requests:
        print(
            f"  forward {list(fwd.targets)}: "
            f"{text(fwd.request_ack, max_bytes)}",
            file=out,
        )
    for hr in actions.hashes:
        size = sum(len(chunk) for chunk in hr.data)
        print(
            f"  hash {size}B -> {text(hr.origin.type, 8)}",
            file=out,
        )
    for write in actions.write_ahead:
        if write.append is not None:
            print(
                f"  persist [{write.append.index}] "
                f"{text(write.append.data.type, max_bytes)}",
                file=out,
            )
        else:
            print(f"  truncate < {write.truncate}", file=out)
    for store in actions.store_requests:
        print(f"  store {text(store.request_ack, max_bytes)}", file=out)
    for commit in actions.commits:
        if commit.batch is not None:
            print(f"  commit {text(commit.batch, max_bytes)}", file=out)
        else:
            print(
                f"  checkpoint seq={commit.checkpoint.seq_no}",
                file=out,
            )
    if actions.state_transfer is not None:
        print(
            f"  state-transfer seq={actions.state_transfer.seq_no}",
            file=out,
        )


def cmd_actions(events, args, out) -> None:
    """Replay the log and print the Actions the state machine emitted at
    the chosen event indices."""
    wanted = set(args.actions_at)
    player = Player(events)
    limit = max(wanted) + 1
    while player.position < limit:
        recorded = player.step()
        if recorded is None:
            break
        index = player.position - 1
        if index not in wanted:
            continue
        print(
            f"=== actions @ event {index} (node {recorded.node_id}, "
            f"{event_kind(recorded.state_event)}) ===",
            file=out,
        )
        render_actions(player.nodes[recorded.node_id].actions, out)
    missing = [i for i in sorted(wanted) if i >= len(events)]
    for i in missing:
        print(f"# event {i} is beyond the log ({len(events)} events)", file=out)


def cmd_timing(events, out) -> None:
    """Replay the log and report per-node state-machine execution time
    (the reference CLI's per-node report, mircat/main.go:497-499)."""
    import time as _time

    player = Player(events)
    wall: dict[int, float] = {}
    applied: dict[int, int] = {}
    while True:
        start = _time.perf_counter()
        recorded = player.step()
        elapsed = _time.perf_counter() - start
        if recorded is None:
            break
        node_id = recorded.node_id
        wall[node_id] = wall.get(node_id, 0.0) + elapsed
        applied[node_id] = applied.get(node_id, 0) + 1
    for node_id in sorted(wall):
        total_ms = 1e3 * wall[node_id]
        per_event_us = 1e6 * wall[node_id] / applied[node_id]
        print(
            f"# node {node_id}: {applied[node_id]} events, "
            f"{total_ms:.1f} ms state-machine time "
            f"({per_event_us:.1f} us/event)",
            file=out,
        )


def cmd_diff(path_a: str, path_b: str, out) -> int:
    events_a = read_log(path_a)
    events_b = read_log(path_b)
    div = first_divergence(events_a, events_b)
    if div is None:
        print(f"# logs identical ({len(events_a)} events)", file=out)
        return 0
    index, ea, eb = div
    print(f"# first divergence at event {index}", file=out)
    for name, recorded in (("a", ea), ("b", eb)):
        if recorded is None:
            print(f"{name}: <log ended>", file=out)
        else:
            print(
                f"{name}: t={recorded.time_ms} node={recorded.node_id} "
                f"{text(recorded.state_event.type, max_bytes=8)}",
                file=out,
            )
    return 1


def main(argv=None, out=sys.stdout) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mirbft_tpu.cat", description=__doc__.split("\n")[0]
    )
    parser.add_argument("log", nargs="?", help="recorded event log (.gz)")
    parser.add_argument("--node", type=int, action="append", default=[],
                        help="only events for this node (repeatable)")
    parser.add_argument("--event-type", action="append", default=[],
                        help="only this StateEvent kind, e.g. EventStep")
    parser.add_argument("--msg-type", action="append", default=[],
                        help="only Step events carrying this msg kind, e.g. Preprepare")
    parser.add_argument("--from-index", type=int, default=None)
    parser.add_argument("--to-index", type=int, default=None)
    parser.add_argument("--summary", action="store_true",
                        help="per-node / per-kind event counts only")
    parser.add_argument("--status-at", type=int, default=None,
                        help="replay to this index and print every node's status "
                             "(-1 = end of log)")
    parser.add_argument("--actions-at", type=int, action="append", default=[],
                        help="replay and print the Actions emitted at this "
                             "event index (repeatable)")
    parser.add_argument("--timing", action="store_true",
                        help="replay and report per-node state-machine "
                             "execution time")
    parser.add_argument("--pretty", action="store_true",
                        help="ASCII status dashboard instead of JSON")
    parser.add_argument("--diff", nargs=2, metavar=("A", "B"),
                        help="diff two logs to their first divergence")
    args = parser.parse_args(argv)

    if args.diff:
        return cmd_diff(args.diff[0], args.diff[1], out)
    if not args.log:
        parser.error("a log path (or --diff A B) is required")

    events = read_log(args.log)
    if args.summary:
        cmd_summary(events, out)
    elif args.actions_at:
        cmd_actions(events, args, out)
    elif args.timing:
        cmd_timing(events, out)
    elif args.status_at is not None:
        cmd_status(events, args, out)
    else:
        cmd_list(events, args, out)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)  # e.g. `... | head` closed the pipe: not an error
