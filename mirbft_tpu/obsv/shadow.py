"""Scalar/vector divergence oracle for the client-tracker ack planes.

Mir assumes replicas are deterministic state machines; replayability
(and every chaos invariant built on it) only holds if the ``_FastAcks``
vector path computes exactly what the scalar reference path
(``ClientReqNo.apply_request_ack`` / ``_step_ack_loop``) would have.
The two live in different representations — uint64 limb masks and a
digest byte-matrix on one side, per-object dicts on the other — so a
bookkeeping bug (a missed refresh, a threshold crossed with ``>`` where
the scalar uses ``>=``) silently forks the replica until something
downstream disagrees.

``audit_tracker`` re-derives, per mirror slot, what the scalar rules
say the dict state must be — weak/strong membership from the popcount
of the agreement mask against the cached quorums, available-list
membership from the weak crossing, tick_class from the reference
classifier — and reports every mismatch as a divergence record.  It is
the ground-truth check the chaos invariant (``chaos.invariants.
check_no_vector_divergence``), the live-cluster audit
(``Node.audit_divergence``) and the bench soak gate all call.

``ShadowSampler`` is the always-on form: hooked into ``step_ack_many``
(via ``hooks.shadow``), it audits the slots each Nth frame touched — a
deterministic stride, no randomness (W12) — bumps
``mirbft_divergence_total{component}`` and flushes the FlightRecorder
once on first divergence so the post-mortem ring captures the frames
that led up to the fork.

Divergence components:

- ``committed``: mirror flags a slot COMMITTED but the object disagrees.
- ``weak`` / ``strong``: dict membership vs mask popcount quorum test.
- ``available``: a weak-quorum canonical request missing from the
  available list.
- ``membership``: structural invariants (strong ⊆ weak ⊆ requests).
- ``tick_class``: the mirror's vectorized tick class vs the reference
  classifier on the live object.
"""

from __future__ import annotations

import os

from .metrics import CardinalityError

#: Audit every Nth ack frame by default.  The audit is O(touched slots)
#: and frames are large on the vector path, so 16 keeps overhead well
#: under the obsv budget while still catching a fork within a handful
#: of frames (asserted by the injected-divergence test).
DEFAULT_STRIDE = 16


def resolve_stride(stride=None) -> int:
    """Sampler stride resolution: explicit value (Config.shadow_stride or
    a direct constructor arg) wins, then the ``MIRBFT_SHADOW_STRIDE`` env
    knob, then :data:`DEFAULT_STRIDE`.  Large-fleet rungs dial this up to
    cut audit overhead without losing the oracle
    (docs/OBSERVABILITY.md#shadow-oracle)."""
    if stride is not None:
        return max(1, int(stride))
    env = os.environ.get("MIRBFT_SHADOW_STRIDE")
    if env:
        return max(1, int(env))
    return DEFAULT_STRIDE


def _slot_ident(fast, slot):
    ci = int(fast.client_of[slot])
    client_id = ci + fast.cid0
    req_no = int(fast.base_arr[ci]) + slot - int(fast.offset_arr[ci])
    return client_id, req_no


def _available_ids(tracker):
    ids = set()
    it = tracker.available_list.iterator()
    while it.has_next():
        ids.add(id(it.next()))
    return ids


def _slot_divergences(fast, slot, crn, avail_ids):
    client_id, req_no = _slot_ident(fast, slot)

    def div(component, detail):
        return {
            "component": component,
            "slot": int(slot),
            "client_id": client_id,
            "req_no": req_no,
            "detail": detail,
        }

    out = []
    flags = int(fast.flags[slot])
    if flags & fast.COMMITTED:
        if crn is None or crn.committed is None:
            out.append(
                div("committed", "mirror COMMITTED but object uncommitted")
            )
        return out
    if crn is None:
        return out

    if not (flags & fast.SLOW) and fast.canon_ok[slot]:
        req = fast.canon_req[slot]
        key = req.ack.digest
        count = fast.combine_agree(slot).bit_count()
        in_weak = key in crn.weak_requests
        if in_weak != (count >= fast.weak_q):
            out.append(
                div(
                    "weak",
                    f"popcount {count} (weak_q {fast.weak_q}) vs "
                    f"weak_requests membership {in_weak}",
                )
            )
        in_strong = key in crn.strong_requests
        if in_strong != (count >= fast.strong_q):
            out.append(
                div(
                    "strong",
                    f"popcount {count} (strong_q {fast.strong_q}) vs "
                    f"strong_requests membership {in_strong}",
                )
            )
        if (
            count >= fast.weak_q
            and not req.garbage
            and id(req) not in avail_ids
        ):
            out.append(
                div("available", "weak-quorum request not in available list")
            )
        # NOTE: agreement voters are deliberately NOT checked against
        # non_null_voters — apply_forward_request bumps agreements
        # out-of-band without a non-null vote (that mask is only the
        # direct-ack spam guard), so agree ⊆ nonnull is not an invariant.

    weak_keys = set(crn.weak_requests)
    if not set(crn.strong_requests) <= weak_keys:
        out.append(div("membership", "strong_requests not subset of weak"))
    if not weak_keys <= set(crn.requests):
        out.append(div("membership", "weak_requests not subset of requests"))

    mirror_cls = int(fast.tick_class[slot])
    ref_cls = fast._classify_tick(crn)
    if mirror_cls != ref_cls:
        out.append(
            div(
                "tick_class",
                f"mirror class {mirror_cls} vs reference {ref_cls}",
            )
        )
    return out


def audit_tracker(tracker, slots=None):
    """Diff the tracker's vector mirror against the scalar rules.

    Returns a list of divergence dicts (empty = provably consistent on
    the audited slots).  ``slots=None`` audits every mirror slot; pass
    an iterable of slot indices to audit a frame's touched subset.
    Vacuously empty when the tracker has no live mirror — the scalar
    path IS the reference, there is nothing to diverge.

    A tracker running the device ack plane (core.device_tracker) is
    audited the same way against its dense arrays — slot indices then
    refer to the device plane's layout (only one plane is ever live).
    """
    fast = getattr(tracker, "_fast", None)
    if fast is None:
        return audit_device_plane(tracker, slots)
    fast.flush_canon_rows()
    avail_ids = _available_ids(tracker)
    if slots is None:
        slots = range(len(fast.canon_req))
    out = []
    for slot in slots:
        crn = fast.canon_crn[slot]
        out.extend(_slot_divergences(fast, slot, crn, avail_ids))
    return out


def audit_device_plane(tracker, slots=None):
    """Diff the device ack plane's dense arrays against the scalar rules
    — the device analogue of the ``_FastAcks`` audit above, with the same
    divergence components.  Flushing pending batches first is the sync
    point; staged (host-authoritative) slots are skipped by contract —
    their array rows are stale by design until the next flush re-derives
    them (docs/DEVICE_TRACKER.md)."""
    dev = getattr(tracker, "_device", None)
    if dev is None:
        return []
    import numpy as np

    from ..core.device_tracker import (
        COMMITTED,
        SLOW,
        classify_tick_device,
    )

    dev.flush(drain=tracker)
    snap = dev.host_snapshot()
    avail_ids = _available_ids(tracker)
    if slots is None:
        slots = range(dev.total)
    staged = dev._staged
    agree = snap["agree"]
    canon_ok = snap["canon_ok"]
    flags_arr = snap["flags"]
    held_arr = snap["held"]
    tick_arr = snap["tick_class"]
    out = []
    for slot in slots:
        if slot in staged:
            continue
        ci = slot // dev.w_pad
        if ci >= dev.n_clients or dev.clients[ci] is None:
            continue  # client-axis padding / dense-id gap: phantom rows
        crn = dev.canon_crn[slot]
        flags = int(flags_arr[slot])
        client_id, req_no = dev._ident(slot)

        def div(component, detail, *, _slot=slot, _cid=client_id,
                _rno=req_no):
            return {
                "component": component,
                "slot": int(_slot),
                "client_id": _cid,
                "req_no": _rno,
                "detail": detail,
            }

        if flags & COMMITTED:
            if crn is None or crn.committed is None:
                out.append(
                    div("committed", "device COMMITTED but object uncommitted")
                )
            continue
        if crn is None:
            continue

        got_cls = int(tick_arr[slot])
        if not (flags & SLOW) and canon_ok[slot]:
            req = dev.canon_req[slot]
            if req is None:
                out.append(
                    div(
                        "membership",
                        "device canonical slot with no materialized request",
                    )
                )
                continue
            key = req.ack.digest
            count = int(np.bitwise_count(agree[slot]).sum())
            in_weak = key in crn.weak_requests
            if in_weak != (count >= dev.weak_q):
                out.append(
                    div(
                        "weak",
                        f"popcount {count} (weak_q {dev.weak_q}) vs "
                        f"weak_requests membership {in_weak}",
                    )
                )
            in_strong = key in crn.strong_requests
            if in_strong != (count >= dev.strong_q):
                out.append(
                    div(
                        "strong",
                        f"popcount {count} (strong_q {dev.strong_q}) vs "
                        f"strong_requests membership {in_strong}",
                    )
                )
            if (
                count >= dev.weak_q
                and not req.garbage
                and id(req) not in avail_ids
            ):
                out.append(
                    div("available", "weak-quorum request not in available list")
                )
            exp_held = key in crn.my_requests and crn.acks_sent > 0
            exp_cls = classify_tick_device(
                False, False, count, exp_held, True, dev.weak_q
            )
            if bool(held_arr[slot]) != exp_held or got_cls != exp_cls:
                out.append(
                    div(
                        "tick_class",
                        f"device class {got_cls} (held {bool(held_arr[slot])})"
                        f" vs reference {exp_cls} (held {exp_held})",
                    )
                )
        elif flags & SLOW:
            my_or_weak = bool(crn.my_requests or crn.weak_requests)
            exp_cls = classify_tick_device(
                False, True, 0, False, my_or_weak, dev.weak_q
            )
            if got_cls != exp_cls:
                out.append(
                    div(
                        "tick_class",
                        f"device slow class {got_cls} vs reference {exp_cls}",
                    )
                )

        weak_keys = set(crn.weak_requests)
        if not set(crn.strong_requests) <= weak_keys:
            out.append(div("membership", "strong_requests not subset of weak"))
        if not weak_keys <= set(crn.requests):
            out.append(div("membership", "weak_requests not subset of requests"))
    return out


class ShadowSampler:
    """Sampling shadow-executor wired into ``step_ack_many``.

    Install via ``hooks.enable(...)`` + ``hooks.shadow = ShadowSampler()``
    or pass ``shadow=`` to ``hooks.enable``.  ``step_ack_many`` calls
    ``on_frame(tracker, msgs)`` after applying each frame; every
    ``stride``-th frame the slots that frame touched are audited.
    """

    def __init__(self, stride=None, registry=None, recorder=None):
        self.stride = resolve_stride(stride)
        self.registry = registry
        self.recorder = recorder
        self.frames = 0
        self.audits = 0
        self.divergences: list = []
        self._dumped = False

    def on_frame(self, tracker, msgs) -> None:
        self.frames += 1
        if self.frames % self.stride:
            return
        plane = getattr(tracker, "_fast", None)
        if plane is None:
            plane = getattr(tracker, "_device", None)
        if plane is None:
            return
        slots = set()
        for msg in msgs:
            ack = msg.type
            slot = plane.slot_of(ack.client_id, ack.req_no)
            if slot is not None:
                slots.add(slot)
        if not slots:
            return
        self.audits += 1
        divs = audit_tracker(tracker, sorted(slots))
        if divs:
            self._record(divs)

    def audit_full(self, tracker) -> list:
        """Audit every slot now (end-of-run sweeps); records like on_frame."""
        divs = audit_tracker(tracker)
        if divs:
            self._record(divs)
        return divs

    def _record(self, divs) -> None:
        from . import hooks

        self.divergences.extend(divs)
        registry = self.registry
        if registry is None and hooks.enabled:
            registry = hooks.metrics
        if registry is not None:
            for d in divs:
                try:
                    registry.counter(
                        "mirbft_divergence_total", component=d["component"]
                    ).inc()
                except CardinalityError:
                    pass
        recorder = self.recorder if self.recorder is not None else hooks.recorder
        if recorder is not None and not self._dumped:
            self._dumped = True
            recorder.record_note(
                "shadow.divergence",
                args={"count": len(divs), "first": divs[0]},
            )
            try:
                recorder.flush("shadow-divergence")
            except Exception:
                pass  # dump_dir unset or unwritable: the note is in the ring
