"""Black-box flight recorder: bounded ring of events, crash-safe dumps.

Every node (thread-cluster replica, OS-process worker, chaos engine)
keeps a ``FlightRecorder``: a fixed-capacity ring of the last N
StateEvents, span milestones, and resource/metric snapshots.  The ring
is preallocated — recording overwrites slots in place, so steady-state
recording does no list growth and stays cheap enough to leave on.

Dumps are *segment files* written atomically (tmp + ``os.replace``)
and rotated over a small fixed set of names, so:

- a SIGKILL mid-write can tear only the tmp file, never a committed
  segment — the previous segment survives intact;
- continuous autoflush (every ``autoflush_every`` records) means even
  a worker that is killed with no chance to run cleanup leaves a
  recent segment behind for the supervisor to reap.

``python -m mirbft_tpu.obsv --postmortem <dir>`` loads every node's
newest segment, converts each to a Chrome trace carrying the same
``clock_sync`` metadata the live tracer emits, and routes them through
``obsv/merge.py`` — one cross-node, clock-aligned causal timeline
ending at the failure.  See docs/OBSERVABILITY.md § Flight recorder.
"""

from __future__ import annotations

import json
import os
import threading
import time

SCHEMA = "mirbft-flight/1"

#: Segment names cycled per node; 2 is enough for the crash-safety
#: argument (the newest committed segment plus the one being replaced).
SEGMENT_KEEP = 2

_KINDS = ("event", "milestone", "resource", "note")


class FlightRecorder:
    """Bounded per-node ring buffer with atomic on-disk dumps.

    ``node`` labels the dump (int node id or a string like ``"bench"``).
    ``dump_dir`` is where segments land; ``None`` keeps the recorder
    purely in-memory (``flush`` then returns the dump dict's path as
    ``None`` but the dump is still available via ``snapshot``).
    ``registry`` (an obsv ``Registry``) receives
    ``mirbft_recorder_records_total{kind}`` /
    ``mirbft_recorder_overwritten_total`` counter deltas at flush time
    — counting at flush keeps ``record()`` off the metrics path.
    """

    def __init__(
        self,
        node,
        dump_dir=None,
        capacity=512,
        autoflush_every=256,
        registry=None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.node = node
        self.dump_dir = dump_dir
        self.capacity = int(capacity)
        self.autoflush_every = int(autoflush_every) if autoflush_every else 0
        self.registry = registry
        self._ring = [None] * self.capacity
        self._next = 0  # monotone record counter; slot = _next % capacity
        self._t0_ns = time.perf_counter_ns()
        self._offsets_ns = {}
        self._flush_seq = 0
        self._kind_counts = {kind: 0 for kind in _KINDS}
        self._counted = {kind: 0 for kind in _KINDS}
        self._counted_overwritten = 0
        self._lock = threading.Lock()
        self.last_dump_path = None
        if dump_dir:
            os.makedirs(dump_dir, exist_ok=True)

    # ------------------------------------------------------------------
    # Recording
    # ------------------------------------------------------------------

    def record(self, kind, name, node=None, args=None):
        """Append one entry to the ring (O(1), no allocation growth)."""
        ts_us = (time.perf_counter_ns() - self._t0_ns) / 1000.0
        entry = {
            "ts_us": ts_us,
            "kind": kind,
            "name": name,
            "node": self.node if node is None else node,
        }
        if args:
            entry["args"] = args
        with self._lock:
            self._ring[self._next % self.capacity] = entry
            self._next += 1
            if kind in self._kind_counts:
                self._kind_counts[kind] += 1
            else:
                self._kind_counts[kind] = 1
            due = (
                self.autoflush_every
                and self.dump_dir
                and self._next % self.autoflush_every == 0
            )
        if due:
            self.flush("auto")

    def record_event(self, name, node=None, args=None):
        self.record("event", name, node, args)

    def record_milestone(self, name, node=None, args=None):
        self.record("milestone", name, node, args)

    def record_resources(self, sample, node=None):
        self.record("resource", "resource.sample", node, sample)

    def record_note(self, name, node=None, args=None):
        """Out-of-band marker (e.g. ``invariant.violation``)."""
        self.record("note", name, node, args)

    def set_clock_offsets(self, offsets_ns):
        """Peer id -> (local - peer) perf_counter_ns, from the transport
        hello handshake; lets --postmortem align this node's dump with
        its peers' exactly like live trace merging."""
        with self._lock:
            self._offsets_ns = {
                str(k): int(v) for k, v in (offsets_ns or {}).items()
            }

    # ------------------------------------------------------------------
    # Dumping
    # ------------------------------------------------------------------

    def snapshot(self, reason="snapshot"):
        """The dump payload dict (oldest-first entries), without I/O."""
        with self._lock:
            total = self._next
            start = max(0, total - self.capacity)
            entries = [
                self._ring[i % self.capacity] for i in range(start, total)
            ]
            dump = {
                "schema": SCHEMA,
                "node": self.node,
                "reason": reason,
                "flush_seq": self._flush_seq,
                "t0_ns": self._t0_ns,
                "offsets_ns": dict(self._offsets_ns),
                "capacity": self.capacity,
                "recorded": total,
                "overwritten": start,
                "entries": entries,
            }
        return dump

    def flush(self, reason="flush"):
        """Write the current ring to an atomic segment file.

        Returns the segment path, or None when no ``dump_dir`` is set.
        Counter deltas since the last flush land on the registry here.
        """
        dump = self.snapshot(reason)
        self._count(dump)
        if not self.dump_dir:
            return None
        with self._lock:
            seq = self._flush_seq
            self._flush_seq += 1
        dump["flush_seq"] = seq
        name = f"node{self.node}-{seq % SEGMENT_KEEP}.flight.json"
        path = os.path.join(self.dump_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(dump, fh)
        os.replace(tmp, path)
        self.last_dump_path = path
        return path

    def _count(self, dump):
        """Emit counter deltas since the last flush onto the registry.

        record() only bumps a plain dict under the ring lock; the
        registry (label lookup, cardinality check) is touched here, off
        the recording hot path.
        """
        if self.registry is None:
            return
        with self._lock:
            deltas = {
                kind: self._kind_counts.get(kind, 0) - self._counted.get(kind, 0)
                for kind in self._kind_counts
            }
            for kind in self._kind_counts:
                self._counted[kind] = self._kind_counts[kind]
            delta_over = dump["overwritten"] - self._counted_overwritten
            self._counted_overwritten = dump["overwritten"]
        for kind, delta in sorted(deltas.items()):
            if delta > 0:
                self.registry.counter(
                    "mirbft_recorder_records_total", kind=kind
                ).inc(delta)
        if delta_over > 0:
            self.registry.counter("mirbft_recorder_overwritten_total").inc(
                delta_over
            )


# ----------------------------------------------------------------------
# Postmortem: dumps -> merged causal timeline
# ----------------------------------------------------------------------


def dump_to_trace(dump):
    """Convert one flight dump into a merge-compatible Chrome trace.

    Entries become ph:"i" instants with ``cat = "flight.<kind>"``
    (merge's flow normalization only touches ``cat == "flow"``, so
    flight instants pass through untouched), plus the ``clock_sync``
    metadata record merge.py aligns on.
    """
    node = dump.get("node", 0)
    events = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": 0,
            "tid": node,
            "args": {"name": f"node {node} flight"},
        },
        {
            "name": "clock_sync",
            "ph": "M",
            "pid": 0,
            "tid": 0,
            "args": {
                "node": node,
                "t0_ns": dump.get("t0_ns", 0),
                "offsets_ns": dump.get("offsets_ns") or {},
            },
        },
    ]
    for entry in dump.get("entries", ()):
        if not entry:
            continue
        event = {
            "name": entry.get("name", "?"),
            "cat": f"flight.{entry.get('kind', 'event')}",
            "ph": "i",
            "s": "t",
            "pid": 0,
            "tid": entry.get("node", node),
            "ts": float(entry.get("ts_us", 0.0)),
        }
        if entry.get("args"):
            event["args"] = entry["args"]
        events.append(event)
    return {"traceEvents": events}


def load_dumps(dump_dir):
    """Newest parseable flight dump per node under ``dump_dir``.

    Walks recursively (the supervisor nests per-node ``flight/``
    directories), skips torn/unparseable files (a crashed writer's tmp
    leftovers), and keeps the highest ``flush_seq`` per node.
    """
    best = {}
    for root, _dirs, files in os.walk(dump_dir):
        for name in sorted(files):
            if not name.endswith(".flight.json"):
                continue
            path = os.path.join(root, name)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    dump = json.load(fh)
            except (OSError, ValueError):
                continue
            if dump.get("schema") != SCHEMA:
                continue
            node = dump.get("node", name)
            seq = dump.get("flush_seq", -1)
            kept = best.get(node)
            if kept is None or seq > kept[0]:
                best[node] = (seq, path, dump)
    return {node: (path, dump) for node, (seq, path, dump) in best.items()}


def annotate_dump(path, **extra):
    """Atomically add keys to a committed dump (supervisor reap stamps
    ``reason="sigkill-reaped"`` etc.). Returns True on success."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            dump = json.load(fh)
    except (OSError, ValueError):
        return False
    dump.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(dump, fh)
    os.replace(tmp, path)
    return True


def render_timeline(merged, limit=200):
    """Human-readable tail of a merged postmortem trace.

    The last ``limit`` instants, oldest first, one line each — the
    timeline by construction ends at the failure (the violation note is
    the last thing recorded before the flush).
    """
    instants = [
        ev
        for ev in merged.get("traceEvents", ())
        if ev.get("ph") == "i" and str(ev.get("cat", "")).startswith("flight.")
    ]
    instants.sort(key=lambda ev: ev.get("ts", 0.0))
    tail = instants[-limit:]
    lines = []
    for ev in tail:
        ts_ms = float(ev.get("ts", 0.0)) / 1000.0
        kind = str(ev.get("cat", ""))[len("flight."):]
        args = ev.get("args")
        detail = ""
        if args:
            detail = " " + json.dumps(args, sort_keys=True, default=str)
        lines.append(
            f"{ts_ms:12.3f}ms node={ev.get('pid')} "
            f"[{kind}] {ev.get('name')}{detail}"
        )
    return "\n".join(lines)


def postmortem(dump_dir, out_path=None, limit=200):
    """Merge every node's newest dump into one causal timeline.

    Returns ``{"nodes", "dumps", "merged", "timeline"}``; writes the
    merged Chrome trace to ``out_path`` when given.  Raises
    FileNotFoundError when the directory holds no parseable dumps.
    """
    from .merge import merge_traces

    dumps = load_dumps(dump_dir)
    if not dumps:
        raise FileNotFoundError(f"no flight dumps under {dump_dir!r}")
    ordered = sorted(dumps.items(), key=lambda item: str(item[0]))
    traces = [dump_to_trace(dump) for _node, (_path, dump) in ordered]
    merged = merge_traces(traces)
    if out_path is not None:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(merged, fh)
    return {
        "nodes": [node for node, _ in ordered],
        "dumps": {str(node): path for node, (path, _dump) in ordered},
        "merged": merged,
        "timeline": render_timeline(merged, limit=limit),
    }
