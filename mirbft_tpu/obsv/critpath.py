"""Per-request critical-path ledger: saturation attribution.

Answers the question the capacity rung raises but cannot answer alone:
when p95 breaks at some arrival rate, *which phase* of a request's
lifecycle absorbed the wait, and *on which node*?  The evidence is the
milestone instants every node's tracer already records (``seq.allocated``
… ``seq.committed``, with ``args.node/seq``), aligned onto the reference
node's clock by :func:`obsv.merge.aligned_events`, optionally joined —
by sequence number — with the loadgen's per-request submit→commit
records (``StepResult.records``), which live on the same
CLOCK_MONOTONIC when loadgen runs on the reference host.

Phase vocabulary (each phase is one edge of the aligned timeline):

    ingress    client submit -> first ``seq.allocated``        (needs join)
    hash       first allocated -> first ``seq.preprepared``    (digest verify
               on the owning leader)
    transmit   first preprepared -> last node's preprepared    (preprepare
               propagation; the straggler node closes it)
    quorum     last preprepared -> first ``seq.commit_quorum`` (prepare +
               commit vote collection)
    commit     first commit_quorum -> first ``seq.committed``  (persist /
               barrier / log apply on the committing node — corroborate
               with the ``mirbft_queue_*`` series for proc.persist /
               proc.barrier)
    apply      committed on the observing node -> client-observed
               commit                                          (needs join)

Without loadgen records the ledger still builds (one row per committed
flow, ingress/apply absent); with them it is one row per committed
request.  The extractor buckets rows into latency percentile bands and
reports, per band, mean residency per phase, the dominant phase, and
the node that most often closed it — the saturation attribution the
``mirbft-capacity/1`` artifact embeds at the knee.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .merge import aligned_events

#: Ledger phases in lifecycle order.
PHASES = ("ingress", "hash", "transmit", "quorum", "commit", "apply")

#: Default latency percentile bands for attribution.
BANDS = ((0.0, 0.50), (0.50, 0.95), (0.95, 0.99), (0.99, 1.0))

_ALLOCATED = "seq.allocated"
_PREPREPARED = "seq.preprepared"
_COMMIT_QUORUM = "seq.commit_quorum"
_COMMITTED = "seq.committed"


@dataclass
class FlowRecord:
    """One committed request's (or flow's) phase residency, microseconds."""

    seq: int
    epoch: int | None = None
    bucket: int | None = None
    client_id: int | None = None
    req_no: int | None = None
    total_us: float = 0.0
    phases: dict = field(default_factory=dict)  # phase -> residency µs
    phase_nodes: dict = field(default_factory=dict)  # phase -> closing node


def _collect_marks(shifted):
    """seq -> {milestone -> {node -> abs_us (earliest)}} plus
    seq -> (epoch, bucket) from milestone instants."""
    marks: dict = {}
    meta: dict = {}
    for abs_us, node, event in shifted:
        if event.get("ph") != "i":
            continue
        name = event.get("name", "")
        if not name.startswith("seq."):
            continue
        args = event.get("args") or {}
        seq = args.get("seq")
        if seq is None:
            continue
        anode = args.get("node", node)
        per_node = marks.setdefault(seq, {}).setdefault(name, {})
        if anode not in per_node or abs_us < per_node[anode]:
            per_node[anode] = abs_us
        if seq not in meta and "epoch" in args and "bucket" in args:
            meta[seq] = (args["epoch"], args["bucket"])
    return marks, meta


def _first(per_node):
    """(abs_us, node) of the earliest node mark, or None."""
    if not per_node:
        return None
    node = min(per_node, key=lambda n: (per_node[n], n))
    return per_node[node], node


def _last(per_node):
    """(abs_us, node) of the latest node mark, or None."""
    if not per_node:
        return None
    node = max(per_node, key=lambda n: (per_node[n], -n))
    return per_node[node], node


def _consensus_phases(seq_marks):
    """The four join-free phases from one seq's milestone marks.

    Returns ``(phases, phase_nodes, allocated_first, committed_first)``;
    edges whose milestones are missing are simply absent (a flow scored
    mid-run can lack its allocated mark).  Residencies are clamped at
    zero: alignment is exact on one host and ~one-way-latency across
    hosts, and a negative residency is attribution noise, not signal.
    """
    phases: dict = {}
    nodes: dict = {}
    alloc = _first(seq_marks.get(_ALLOCATED, {}))
    pp_first = _first(seq_marks.get(_PREPREPARED, {}))
    pp_last = _last(seq_marks.get(_PREPREPARED, {}))
    cq = _first(seq_marks.get(_COMMIT_QUORUM, {}))
    committed = _first(seq_marks.get(_COMMITTED, {}))

    def edge(phase, start, end):
        if start is not None and end is not None:
            phases[phase] = max(0.0, end[0] - start[0])
            nodes[phase] = end[1]

    edge("hash", alloc, pp_first)
    edge("transmit", pp_first, pp_last)
    edge("quorum", pp_last, cq)
    edge("commit", cq, committed)
    return phases, nodes, alloc, committed


def build_ledger(traces, records=None):
    """Build the per-request ledger from per-node Chrome traces.

    ``traces`` — iterable of parsed trace dicts (clock_sync metadata
    aligns them; see merge.py).  ``records`` — optional loadgen
    per-request dicts (``StepResult.records``); when given, the ledger
    is one row per committed request (ingress/apply resolved from the
    submit/commit stamps), otherwise one row per committed flow.
    Returns a list of :class:`FlowRecord` sorted by ``total_us``.
    """
    shifted, _plans = aligned_events(traces)
    marks, meta = _collect_marks(shifted)

    ledger = []
    if records:
        for rec in records:
            seq = rec.get("seq")
            seq_marks = marks.get(seq)
            if seq_marks is None:
                continue  # no trace evidence for this commit
            phases, nodes, alloc, _committed = _consensus_phases(seq_marks)
            submit_us = rec["submit_ns"] / 1000.0
            commit_us = rec["commit_ns"] / 1000.0
            if alloc is not None:
                phases["ingress"] = max(0.0, alloc[0] - submit_us)
                nodes["ingress"] = alloc[1]
            obs_node = rec.get("node")
            committed_at = marks.get(seq, {}).get(_COMMITTED, {})
            applied = committed_at.get(obs_node)
            if applied is None:
                applied_first = _first(committed_at)
                applied = applied_first[0] if applied_first else None
            if applied is not None:
                phases["apply"] = max(0.0, commit_us - applied)
                nodes["apply"] = obs_node
            epoch, bucket = meta.get(seq, (None, None))
            ledger.append(
                FlowRecord(
                    seq=seq,
                    epoch=epoch,
                    bucket=bucket,
                    client_id=rec.get("client_id"),
                    req_no=rec.get("req_no"),
                    total_us=max(0.0, commit_us - submit_us),
                    phases=phases,
                    phase_nodes=nodes,
                )
            )
    else:
        for seq, seq_marks in marks.items():
            phases, nodes, alloc, committed = _consensus_phases(seq_marks)
            if alloc is None or committed is None:
                continue
            epoch, bucket = meta.get(seq, (None, None))
            ledger.append(
                FlowRecord(
                    seq=seq,
                    epoch=epoch,
                    bucket=bucket,
                    total_us=max(0.0, committed[0] - alloc[0]),
                    phases=phases,
                    phase_nodes=nodes,
                )
            )
    ledger.sort(key=lambda r: r.total_us)
    return ledger


def attribute(ledger, bands=BANDS):
    """Per-band saturation attribution over a sorted ledger.

    Each band ``(lo, hi)`` covers ledger rows ranked by total latency in
    ``[lo*n, hi*n)`` (the top band includes the slowest row).  Per band:
    mean residency per phase, the dominant phase (largest mean), and the
    node that most often closed it.  Bands with no rows are omitted.
    """
    rows = sorted(ledger, key=lambda r: r.total_us)
    n = len(rows)
    out = []
    for lo, hi in bands:
        start = int(lo * n)
        stop = n if hi >= 1.0 else int(hi * n)
        band_rows = rows[start:stop]
        if not band_rows:
            continue
        phase_sum = {phase: 0.0 for phase in PHASES}
        phase_count = {phase: 0 for phase in PHASES}
        node_votes: dict = {phase: {} for phase in PHASES}
        for row in band_rows:
            for phase, us in row.phases.items():
                phase_sum[phase] += us
                phase_count[phase] += 1
                node = row.phase_nodes.get(phase)
                if node is not None:
                    votes = node_votes[phase]
                    votes[node] = votes.get(node, 0) + 1
        phase_us = {
            phase: phase_sum[phase] / phase_count[phase]
            for phase in PHASES
            if phase_count[phase]
        }
        if not phase_us:
            continue
        dominant = max(phase_us, key=lambda p: (phase_us[p], p))
        votes = node_votes[dominant]
        dominant_node = (
            max(votes, key=lambda nd: (votes[nd], -nd)) if votes else None
        )
        out.append(
            {
                "band": f"p{lo * 100:g}-p{hi * 100:g}",
                "count": len(band_rows),
                "total_us_mean": sum(r.total_us for r in band_rows)
                / len(band_rows),
                "phase_us": phase_us,
                "dominant_phase": dominant,
                "dominant_node": dominant_node,
            }
        )
    return out


def attribution_table(attribution):
    """ASCII table for the ``--critpath`` CLI (µs means per band)."""
    header = f"{'band':<10} {'count':>6} {'total_us':>10} "
    header += " ".join(f"{phase:>9}" for phase in PHASES)
    header += f"  {'dominant':<10} {'node':>4}"
    lines = [header, "-" * len(header)]
    if not attribution:
        lines.append("(no joined flows — is clock_sync metadata present?)")
    for band in attribution:
        cells = " ".join(
            f"{band['phase_us'].get(phase, 0.0):>9.1f}" for phase in PHASES
        )
        node = band["dominant_node"]
        lines.append(
            f"{band['band']:<10} {band['count']:>6} "
            f"{band['total_us_mean']:>10.1f} {cells}  "
            f"{band['dominant_phase']:<10} "
            f"{node if node is not None else '-':>4}"
        )
    return "\n".join(lines)


def ledger_from_dir(path):
    """Load a run directory: per-node ``trace*.json`` files — flat, or
    one level down in ``node*/`` subdirectories (the cluster
    supervisor's root layout) — plus an optional ``records.json``
    (loadgen per-request records).  Returns ``(ledger, n_traces)``."""
    trace_paths = sorted(
        os.path.join(path, name)
        for name in os.listdir(path)
        if name.startswith("trace") and name.endswith(".json")
    )
    if not trace_paths:
        trace_paths = sorted(
            os.path.join(path, sub, name)
            for sub in os.listdir(path)
            if sub.startswith("node")
            and os.path.isdir(os.path.join(path, sub))
            for name in os.listdir(os.path.join(path, sub))
            if name.startswith("trace") and name.endswith(".json")
        )
    traces = []
    for trace_path in trace_paths:
        with open(trace_path, "r", encoding="utf-8") as f:
            traces.append(json.load(f))
    records = None
    records_path = os.path.join(path, "records.json")
    if os.path.exists(records_path):
        with open(records_path, "r", encoding="utf-8") as f:
            records = json.load(f)
    return build_ledger(traces, records=records), len(traces)
