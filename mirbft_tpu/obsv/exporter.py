"""Stdlib-only HTTP exposition for a running node.

A tiny ``http.server`` ThreadingHTTPServer on a daemon thread serving:

- ``GET /metrics``  — Prometheus text 0.0.4 rendered by the registry's
  catalog renderer (this module is the *only* place outside tests where
  registry internals meet a socket; lint rule W8 bans ``http.server``
  elsewhere in ``mirbft_tpu``).
- ``GET /status``   — JSON produced by a caller-supplied callable
  (``status.state_machine_status(...).to_json()`` on the runtime node).
- ``GET /healthz``  — liveness: 200 ``{"ok": true}`` while serving.
- ``GET /dump``     — flush the node's flight recorder to an on-disk
  segment and return its path (503 when no recorder is wired); the
  operator-triggered counterpart of the crash-path auto-dump.

Off by default: the runtime node only starts one when
``Config.metrics_port`` is set (0 binds an ephemeral port — the test
default).  ``close()`` is idempotent and wired into node stop and the
serializer's crash path, so chaos crash schedules tear the socket down
with the node.

Endpoint callables run on the server's request threads; they must be
thread-safe (the registry is; node.status() does a serializer
round-trip with a timeout).  A callable returning ``None`` maps to 503,
a raising callable to 500 — a scrape can never take the node down.
"""

from __future__ import annotations

import http.server
import json
import threading


class ObsvExporter:
    """Serve /metrics, /status and /healthz for one node."""

    def __init__(
        self,
        host="127.0.0.1",
        port=0,
        registry_fn=None,
        status_fn=None,
        node_id=None,
        dump_fn=None,
    ):
        self._registry_fn = registry_fn
        self._status_fn = status_fn
        self._node_id = node_id
        self._dump_fn = dump_fn
        self._closed = False
        # Reported by /healthz.  True by default (a node that serves is
        # live); the cluster runner's worker flips it False before wiring
        # and True once the transport mesh is connected, so the
        # supervisor's readiness handshake is one HTTP poll.
        self.ready = True
        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # Scrapes are frequent; stay silent on stderr.
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                try:
                    if self.path == "/metrics":
                        body, ctype, code = exporter._metrics()
                    elif self.path == "/status":
                        body, ctype, code = exporter._status()
                    elif self.path == "/healthz":
                        body, ctype, code = exporter._healthz()
                    elif self.path == "/dump":
                        body, ctype, code = exporter._dump()
                    else:
                        body, ctype, code = "not found\n", "text/plain", 404
                except Exception as exc:  # noqa: BLE001 — scrape must not kill the node
                    body, ctype, code = f"error: {exc}\n", "text/plain", 500
                payload = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"obsv-exporter-{self._server.server_address[1]}",
            daemon=True,
        )
        self._thread.start()

    @property
    def address(self):
        """``(host, port)`` actually bound (port resolved when 0)."""
        return self._server.server_address[:2]

    def _metrics(self):
        registry = self._registry_fn() if self._registry_fn else None
        if registry is None:
            return (
                "# mirbft: observability hooks disabled (hooks.enable() to scrape)\n",
                "text/plain; version=0.0.4",
                200,
            )
        return registry.prometheus_text(), "text/plain; version=0.0.4", 200

    def _status(self):
        status = self._status_fn() if self._status_fn else None
        if status is None:
            return (
                json.dumps({"error": "status unavailable"}),
                "application/json",
                503,
            )
        if not isinstance(status, str):
            status = json.dumps(status)
        return status, "application/json", 200

    def _dump(self):
        path = self._dump_fn() if self._dump_fn else None
        if path is None:
            return (
                json.dumps({"error": "no flight recorder wired"}),
                "application/json",
                503,
            )
        return json.dumps({"ok": True, "path": path}), "application/json", 200

    def _healthz(self):
        body = {"ok": True, "ready": bool(self.ready)}
        if self._node_id is not None:
            body["node_id"] = self._node_id
        return json.dumps(body), "application/json", 200

    def close(self, timeout=5.0):
        """Stop serving and join the server thread; idempotent."""
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=timeout)
