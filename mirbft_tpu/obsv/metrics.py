"""Zero-dependency metrics registry: counters, gauges, fixed-bucket
histograms, with Prometheus text exposition and a JSON dump.

Design points:

- **Catalog-enforced names.** Every metric the codebase registers must be
  declared in :data:`CATALOG` (name -> help text); a strict registry
  raises on unknown names.  The catalog is the single source of truth the
  docs test checks against ``docs/OBSERVABILITY.md``, so an undocumented
  metric cannot ship.
- **Cheap no-op handles.** ``null_registry()`` hands out shared singleton
  handles whose ``inc``/``set``/``observe`` are empty methods — callers
  that cache a handle pay one no-op call when observability is off.  The
  even cheaper path (used on hot loops) is the ``hooks.enabled`` branch,
  which skips the handle lookup entirely.
- **Label sets are kwargs.** ``registry.counter("x_total", path="device")``
  keys the series on the sorted label items, so the same call site always
  returns the same underlying series.
- **Label names and cardinality are budgeted.** :data:`CATALOG_LABELS`
  declares the only label names each metric may carry, and
  :data:`CARDINALITY` caps how many label-set series a family may grow
  (default :data:`DEFAULT_CARDINALITY`).  A strict registry *rejects*
  registration beyond the documented bound with
  :class:`CardinalityError` — callers on hot paths (hooks.milestone)
  catch it and degrade to "instant recorded, counter skipped" rather
  than let an epoch storm OOM the scrape path.
- **Monotonic-only.** Nothing in this module reads a clock; durations are
  observed by callers from ``time.perf_counter`` deltas (W7 lint).
"""

from __future__ import annotations

import bisect
import json
import threading

# name -> help text.  Keep sorted; tests assert every key appears in
# docs/OBSERVABILITY.md.
CATALOG = {
    "mirbft_ack_batch_size": "RequestAck frame/batch sizes entering an ack plane, by plane (host = step_ack_many frames, device = kernel flushes).",
    "mirbft_ack_events_total": "RequestAck events absorbed by an ack plane, by plane (host _FastAcks/scalar path vs device bitmask plane).",
    "mirbft_app_applied_index": "The commit stream's applied index: ops delivered exactly-once to the registered state machine, in consensus order.",
    "mirbft_app_read_barrier_wait_seconds": "Seconds a committed-mode read waited behind the read-index barrier (applied index covering the read's issue-point frontier).",
    "mirbft_app_reads_total": "KV service reads, by mode (committed/stale) and outcome (ok/not_found/timeout).",
    "mirbft_app_writes_total": "KV service writes, by mode (put/delete/cas) and outcome (ok/not_found/cas_conflict/malformed/timeout/rejected).",
    "mirbft_bench_stage_compile_seconds": "bench.py per-stage warmup/compile seconds (JAX/Mosaic compiles triggered before the timed window).",
    "mirbft_bench_stage_seconds": "bench.py per-stage wall-clock seconds.",
    "mirbft_bucket_backlog": "Per-bucket consensus backlog: sequences allocated but not yet committed in the active epoch, sampled on tick (the skew/imbalance signal).",
    "mirbft_byzantine_rejections_total": "Adversarial inputs rejected, by kind (corrupt/equivocate/stale_ack/oversized_batch/oversized_payload/oversized_digest/oversized_snapshot_chunk/malformed).",
    "mirbft_checkpoint_lag_seqnos": "Sequence distance from this node's checkpoint window to the newest 2f+1-certified above-window checkpoint (0 when caught up; the state-transfer trigger).",
    "mirbft_censored_commit_epochs": "Epoch rotations a censored-but-retried request needed before committing, per scenario.",
    "mirbft_cert_aggregate_verifies_total": "Aggregate-signature certificate verifications through crypto/qc.py, by outcome (ok/rejected).",
    "mirbft_chaos_dropped_total": "Messages dropped by chaos manglers, per scenario.",
    "mirbft_chaos_duplicated_total": "Messages duplicated by chaos manglers, per scenario.",
    "mirbft_chaos_live_recovery_ms": "Live chaos scenario: wall ms from the last heal/restart to convergence.",
    "mirbft_chaos_recovery_ms": "Chaos scenario recovery time: completion minus last disruption end (simulated ms).",
    "mirbft_crypto_flush_seconds": "Blocking wall time of one crypto-plane flush/launch/readback.",
    "mirbft_crypto_flush_total": "Crypto-plane flush/launch/readback operations, by plane and path.",
    "mirbft_crypto_items_total": "Digests or signature verdicts produced, by plane and path (device/host/readback/rescued/inline/batch).",
    "mirbft_crypto_speculative_evictions_total": "Speculatively admitted requests evicted before ordering because their signature verdict came back false.",
    "mirbft_crypto_verify_batch_size": "Signature-verification burst sizes entering the batched verify stage, by path (rlc/device/ingress/batch/host/readback/rescued).",
    "mirbft_device_hbm_bytes": "Accelerator bytes_in_use reported by the backend's memory_stats (0 on backends without it), sampled by obsv.resources.",
    "mirbft_device_kernel_seconds": "Wall time per instrumented device-plane kernel call (blocking until ready unless the entry point opts out).",
    "mirbft_device_live_buffers": "Live jax arrays held by the process, sampled by obsv.resources.",
    "mirbft_device_live_buffer_bytes": "Total bytes of live jax arrays, sampled by obsv.resources.",
    "mirbft_device_retraces_total": "New abstract-shape signatures seen per device-plane function (each is one jit retrace; growth past the budget fails obsv --diff).",
    "mirbft_device_transfer_bytes_total": "Estimated host<->device traffic of instrumented kernel calls, by direction (h2d from argument nbytes, d2h from result nbytes).",
    "mirbft_divergence_total": "Scalar/vector divergences found by the shadow oracle, by component (committed/weak/strong/available/membership/tick_class).",
    "mirbft_engine_events_total": "Events processed by a testengine Recorder run.",
    "mirbft_engine_sim_ms": "Final simulated clock of a testengine Recorder run.",
    "mirbft_epoch_change_seconds": "Wall time from constructing an epoch change to activating the new epoch, per node observation.",
    "mirbft_epoch_events_total": "Epoch-change milestones (changing/active), by event and epoch.",
    "mirbft_flow_abandoned_total": "Open-flow table entries evicted before a terminal milestone (requests censored/dropped under chaos; bounded-eviction pressure).",
    "mirbft_mac_rejections_total": "Replica-channel frames rejected by MAC authentication, by kind (bad_mac/short_frame/unsealed).",
    "mirbft_proc_phase_seconds": "Runtime processor wall time per phase (persist/transmit/hash/commit or pooled total).",
    "mirbft_proc_stage_queue_depth": "Pipelined processor: batches queued at each stage hand-off.",
    "mirbft_queue_depth": "Items queued in a bounded hot-path queue, by queue name (emitted only through the obsv.bqueue shim; lint rule W19).",
    "mirbft_queue_saturated_total": "Put attempts that found a bounded hot-path queue at capacity (blocked, dropped-oldest, or forced a flush), by queue name.",
    "mirbft_queue_wait_seconds": "Seconds an item spent inside a bounded hot-path queue (enqueue to dequeue), by queue name.",
    "mirbft_reconfig_committed_total": "Reconfiguration requests committed through the ordered broadcast path, by kind (network_config/new_client/remove_client/unknown).",
    "mirbft_reconfig_adopted_total": "Reconfiguration activations: stable checkpoints whose pending reconfigurations were adopted (trackers reinitialized into the new NetworkState.config).",
    "mirbft_recorder_overwritten_total": "Flight-recorder ring slots overwritten before ever reaching a dump.",
    "mirbft_recorder_records_total": "Flight-recorder entries recorded, by kind (event/milestone/resource/note).",
    "mirbft_reqstore_appends_total": "Request-store record appends.",
    "mirbft_reqstore_compactions_total": "Live intent-log compactions (dead-weight rewrites reclaiming disk).",
    "mirbft_request_duplicates_total": "Duplicate client submissions absorbed by request dedup, by reason (retired/committed/stored).",
    "mirbft_resource_disk_bytes": "On-disk bytes under a store directory (wal/reqstore), sampled by obsv.resources.",
    "mirbft_resource_open_fds": "Open file descriptors in this process, sampled by obsv.resources.",
    "mirbft_resource_rss_bytes": "Resident set size of this process in bytes, sampled by obsv.resources.",
    "mirbft_resource_threads": "Live Python threads in this process, sampled by obsv.resources.",
    "mirbft_reqstore_group_commit_batches": "Request-store sync tickets satisfied by group-commit fsyncs.",
    "mirbft_reqstore_group_sync_wait_seconds": "Per-waiter request-store group-commit latency (ticket issue to durable).",
    "mirbft_seq_milestones_total": "Consensus milestones reached, by milestone name, epoch, and bucket.",
    "mirbft_reqstore_fsync_seconds": "Wall time per request-store fsync.",
    "mirbft_reqstore_fsyncs_total": "Request-store fsync calls.",
    "mirbft_sm_actions_total": "Actions emitted by StateMachine.apply_event, by kind.",
    "mirbft_sm_apply_seconds": "Wall time per StateMachine.apply_event call.",
    "mirbft_sm_events_total": "State-machine events applied, by event type.",
    "mirbft_transfer_chunks_total": "State-transfer chunk frames, by outcome (served/received/rejected_corrupt/rejected_oversized/stale).",
    "mirbft_transfer_snapshots_total": "State-transfer snapshot outcomes (served/nacked/installed/resumed_staged/donor_failover/retry/failed).",
    "mirbft_transport_frames_per_write": "Frames coalesced into each transport sendall.",
    "mirbft_transport_frames_total": "Transport frames, by outcome (enqueued/sent/dropped_overflow/dropped_closed/send_failure/dropped_unknown/dropped_fault).",
    "mirbft_transport_reconnects_total": "Transport dial attempts, by outcome (connected/failed/timeout/faulted).",
    "mirbft_wal_appends_total": "WAL record appends.",
    "mirbft_wal_fsync_seconds": "Wall time per WAL fsync.",
    "mirbft_wal_fsyncs_total": "WAL fsync calls.",
    "mirbft_wal_group_commit_batches": "WAL sync tickets satisfied by group-commit fsyncs.",
    "mirbft_wal_group_sync_wait_seconds": "Per-waiter WAL group-commit latency (ticket issue to durable).",
}

# name -> allowed label names.  A strict registry rejects any label key
# outside this set, so a new dimension cannot ship undocumented (the
# docs test checks every label name below against docs/OBSERVABILITY.md).
CATALOG_LABELS = {
    "mirbft_ack_batch_size": ("plane",),
    "mirbft_ack_events_total": ("plane",),
    "mirbft_app_applied_index": (),
    "mirbft_app_read_barrier_wait_seconds": (),
    "mirbft_app_reads_total": ("mode", "outcome"),
    "mirbft_app_writes_total": ("mode", "outcome"),
    "mirbft_bench_stage_compile_seconds": ("stage",),
    "mirbft_bench_stage_seconds": ("stage",),
    "mirbft_bucket_backlog": ("bucket",),
    "mirbft_byzantine_rejections_total": ("kind",),
    "mirbft_checkpoint_lag_seqnos": (),
    "mirbft_censored_commit_epochs": ("scenario",),
    "mirbft_cert_aggregate_verifies_total": ("outcome",),
    "mirbft_chaos_dropped_total": ("scenario",),
    "mirbft_chaos_duplicated_total": ("scenario",),
    "mirbft_chaos_live_recovery_ms": ("scenario",),
    "mirbft_chaos_recovery_ms": ("scenario",),
    "mirbft_crypto_flush_seconds": ("plane",),
    "mirbft_crypto_flush_total": ("plane", "path"),
    "mirbft_crypto_items_total": ("plane", "path"),
    "mirbft_crypto_speculative_evictions_total": (),
    "mirbft_crypto_verify_batch_size": ("path",),
    "mirbft_device_hbm_bytes": (),
    "mirbft_device_kernel_seconds": ("kernel",),
    "mirbft_device_live_buffers": (),
    "mirbft_device_live_buffer_bytes": (),
    "mirbft_device_retraces_total": ("fn",),
    "mirbft_device_transfer_bytes_total": ("direction",),
    "mirbft_divergence_total": ("component",),
    "mirbft_engine_events_total": ("stage",),
    "mirbft_engine_sim_ms": ("stage",),
    "mirbft_epoch_change_seconds": (),
    "mirbft_epoch_events_total": ("event", "epoch"),
    "mirbft_flow_abandoned_total": (),
    "mirbft_mac_rejections_total": ("kind",),
    "mirbft_proc_phase_seconds": ("phase",),
    "mirbft_proc_stage_queue_depth": ("stage",),
    "mirbft_queue_depth": ("queue",),
    "mirbft_queue_saturated_total": ("queue",),
    "mirbft_queue_wait_seconds": ("queue",),
    "mirbft_reconfig_committed_total": ("kind",),
    "mirbft_reconfig_adopted_total": (),
    "mirbft_recorder_overwritten_total": (),
    "mirbft_recorder_records_total": ("kind",),
    "mirbft_reqstore_appends_total": (),
    "mirbft_reqstore_compactions_total": (),
    "mirbft_request_duplicates_total": ("reason",),
    "mirbft_resource_disk_bytes": ("store",),
    "mirbft_resource_open_fds": (),
    "mirbft_resource_rss_bytes": (),
    "mirbft_resource_threads": (),
    "mirbft_reqstore_group_commit_batches": (),
    "mirbft_reqstore_group_sync_wait_seconds": (),
    "mirbft_reqstore_fsync_seconds": (),
    "mirbft_reqstore_fsyncs_total": (),
    "mirbft_seq_milestones_total": ("milestone", "epoch", "bucket"),
    "mirbft_sm_actions_total": ("kind",),
    "mirbft_sm_apply_seconds": (),
    "mirbft_sm_events_total": ("type",),
    "mirbft_transfer_chunks_total": ("outcome",),
    "mirbft_transfer_snapshots_total": ("outcome",),
    "mirbft_transport_frames_per_write": (),
    "mirbft_transport_frames_total": ("outcome",),
    "mirbft_transport_reconnects_total": ("outcome",),
    "mirbft_wal_appends_total": (),
    "mirbft_wal_fsync_seconds": (),
    "mirbft_wal_fsyncs_total": (),
    "mirbft_wal_group_commit_batches": (),
    "mirbft_wal_group_sync_wait_seconds": (),
}

# Per-family series budgets.  Most label spaces here are small and
# closed (phases, outcomes, planes); DEFAULT_CARDINALITY covers them
# with wide margin.  mirbft_seq_milestones_total is the one open-ended
# family — milestone(6) x epoch x bucket — so it gets an explicit
# larger bound.  Both numbers are part of the documented contract in
# docs/OBSERVABILITY.md.
DEFAULT_CARDINALITY = 256
CARDINALITY = {
    "mirbft_seq_milestones_total": 4096,
    # Two closed planes (host/device) x {counter, histogram}: keep the
    # budget tight so a label typo cannot silently mint series.
    "mirbft_ack_batch_size": 4,
    "mirbft_ack_events_total": 4,
    # Closed outcome sets (see CATALOG help text): a typo'd outcome label
    # must fail loudly instead of minting series.
    "mirbft_transfer_chunks_total": 8,
    "mirbft_transfer_snapshots_total": 8,
    # 2 read modes x 3 outcomes; 3 write ops x 6 outcomes.
    "mirbft_app_reads_total": 8,
    "mirbft_app_writes_total": 24,
    # One series per named bounded queue: 4 processor stages + app apply
    # + device staging + one per transport peer (mp clusters run <= a few
    # dozen peers per process).  Over-budget registration degrades to
    # "series dropped" inside the bqueue shim, never an exception on the
    # hot path.
    "mirbft_queue_depth": 64,
    "mirbft_queue_saturated_total": 64,
    "mirbft_queue_wait_seconds": 64,
    # One series per active-epoch bucket (bounded by the leader set).
    "mirbft_bucket_backlog": 256,
    # Closed kind set (network_config/new_client/remove_client/unknown):
    # a typo'd kind must fail loudly instead of minting series.
    "mirbft_reconfig_committed_total": 4,
    # Closed crypto label spaces: verify paths (the record_flush path
    # vocabulary: rlc/device/ingress/batch/host/readback/rescued),
    # rejection kinds (bad_mac/short_frame/unsealed), cert outcomes
    # (ok/rejected).
    "mirbft_crypto_verify_batch_size": 8,
    "mirbft_mac_rejections_total": 4,
    "mirbft_cert_aggregate_verifies_total": 4,
}


class CardinalityError(ValueError):
    """A metric family tried to grow beyond its series budget."""

# Latency buckets (seconds): 5us .. 5s, roughly geometric.  Chosen to
# resolve both sub-ms host hashing and multi-second device round trips.
DEFAULT_BUCKETS = (
    0.000005,
    0.00002,
    0.0001,
    0.0005,
    0.002,
    0.01,
    0.05,
    0.25,
    1.0,
    5.0,
)

# Size buckets (rows) for mirbft_ack_batch_size: powers of four from a
# single ack up to the device plane's max kernel bucket (65536 rows).
ACK_BATCH_BUCKETS = (1, 4, 16, 64, 256, 1024, 4096, 16384, 65536)


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge:
    """Point-in-time value (set, not accumulated)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value):
        self.value = value


class Histogram:
    """Fixed upper-bound bucket histogram with sum and count.

    ``bucket_counts[i]`` counts observations <= ``uppers[i]``
    (non-cumulative per bucket; exposition cumulates per Prometheus
    convention).  Observations above the last bound land only in +Inf
    (i.e. in ``count``/``sum`` but no finite bucket).
    """

    __slots__ = ("uppers", "bucket_counts", "sum", "count")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.uppers = tuple(sorted(buckets))
        self.bucket_counts = [0] * len(self.uppers)
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        self.sum += value
        self.count += 1
        i = bisect.bisect_left(self.uppers, value)
        if i < len(self.uppers):
            self.bucket_counts[i] += 1


class _NullCounter:
    __slots__ = ()
    value = 0

    def inc(self, n=1):
        pass


class _NullGauge:
    __slots__ = ()
    value = 0.0

    def set(self, value):
        pass


class _NullHistogram:
    __slots__ = ()
    uppers = ()
    bucket_counts = ()
    sum = 0.0
    count = 0

    def observe(self, value):
        pass


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


class NullRegistry:
    """Shared no-op registry: every factory returns the same singleton
    handle, so disabled instrumentation allocates nothing."""

    def counter(self, name, **labels):
        return NULL_COUNTER

    def gauge(self, name, **labels):
        return NULL_GAUGE

    def histogram(self, name, buckets=DEFAULT_BUCKETS, **labels):
        return NULL_HISTOGRAM

    def snapshot(self):
        return {}

    def to_json(self):
        return "{}"

    def prometheus_text(self):
        return ""


NULL_REGISTRY = NullRegistry()


def null_registry():
    return NULL_REGISTRY


class Registry:
    """Live registry.  Thread-safe for registration (runtime processors
    record from pool lanes); individual metric mutation is a single
    int/float update, which CPython makes atomic enough for counters.
    """

    def __init__(self, strict=True):
        self._strict = strict
        self._lock = threading.Lock()
        # name -> {label_items_tuple -> metric}
        self._families = {}
        # name -> "counter" | "gauge" | "histogram"
        self._kinds = {}

    def _get(self, name, labels, kind, factory):
        if self._strict:
            if name not in CATALOG:
                raise KeyError(
                    f"metric {name!r} is not in obsv.metrics.CATALOG; "
                    "declare it (and document it in docs/OBSERVABILITY.md)"
                )
            allowed = CATALOG_LABELS.get(name, ())
            for label in labels:
                if label not in allowed:
                    raise KeyError(
                        f"label {label!r} is not declared for {name!r} in "
                        "obsv.metrics.CATALOG_LABELS; declare it (and "
                        "document it in docs/OBSERVABILITY.md)"
                    )
        key = tuple(sorted(labels.items()))
        with self._lock:
            family = self._families.get(name)
            if family is None:
                family = self._families[name] = {}
                self._kinds[name] = kind
            elif self._kinds[name] != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {self._kinds[name]}"
                )
            metric = family.get(key)
            if metric is None:
                if self._strict:
                    budget = CARDINALITY.get(name, DEFAULT_CARDINALITY)
                    if len(family) >= budget:
                        raise CardinalityError(
                            f"metric {name!r} is at its cardinality budget "
                            f"({budget} series); refusing to register "
                            f"labels {dict(key)!r}"
                        )
                metric = family[key] = factory()
            return metric

    def counter(self, name, **labels):
        return self._get(name, labels, "counter", Counter)

    def gauge(self, name, **labels):
        return self._get(name, labels, "gauge", Gauge)

    def histogram(self, name, buckets=DEFAULT_BUCKETS, **labels):
        return self._get(name, labels, "histogram", lambda: Histogram(buckets))

    def snapshot(self):
        """Plain-data dump: name -> {kind, help, series: [{labels, ...}]}."""
        out = {}
        with self._lock:
            for name in sorted(self._families):
                kind = self._kinds[name]
                series = []
                for key in sorted(self._families[name]):
                    metric = self._families[name][key]
                    entry = {"labels": dict(key)}
                    if kind == "histogram":
                        entry["count"] = metric.count
                        entry["sum"] = metric.sum
                        entry["buckets"] = {
                            str(u): c
                            for u, c in zip(metric.uppers, metric.bucket_counts)
                        }
                    else:
                        entry["value"] = metric.value
                    series.append(entry)
                out[name] = {
                    "kind": kind,
                    "help": CATALOG.get(name, ""),
                    "series": series,
                }
        return out

    def to_json(self, indent=None):
        return json.dumps(self.snapshot(), indent=indent)

    def prometheus_text(self):
        """Prometheus text exposition format 0.0.4."""
        lines = []
        snap = self.snapshot()
        for name, family in snap.items():
            lines.append(f"# HELP {name} {family['help']}")
            lines.append(f"# TYPE {name} {family['kind']}")
            for entry in family["series"]:
                labels = entry["labels"]
                if family["kind"] == "histogram":
                    cumulative = 0
                    for upper, count in entry["buckets"].items():
                        cumulative += count
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels({**labels, 'le': upper})} "
                            f"{cumulative}"
                        )
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels({**labels, 'le': '+Inf'})} "
                        f"{entry['count']}"
                    )
                    lines.append(
                        f"{name}_sum{_render_labels(labels)} {entry['sum']}"
                    )
                    lines.append(
                        f"{name}_count{_render_labels(labels)} {entry['count']}"
                    )
                else:
                    lines.append(
                        f"{name}{_render_labels(labels)} {entry['value']}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


def _render_labels(labels):
    if not labels:
        return ""
    body = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + body + "}"


def _escape(value):
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
