"""Timeline-diff regression gate: compare two trace/bench artifacts.

``python -m mirbft_tpu.obsv --diff A B [--threshold PCT]`` loads two
artifacts, extracts a flat ``{series_name: value}`` mapping from each,
and reports per-series deltas with a machine-readable verdict.  Exit
status is the gate: nonzero iff any gated series regressed by at least
the threshold (so CI can chain BENCH_r*.json artifacts rung-to-rung).

Supported artifact shapes (auto-detected):

- **Chrome trace JSON** (``traceEvents`` key): fed through the
  consensus TimelineProfiler; series are
  ``phase.<name>.{p50,p95,p99}_ms`` plus ``phase.<name>.count``.
- **bench.py JSON** (``metric``/``stages`` keys): numeric top-level
  fields (rates, p99s, walls), per-stage ``seconds`` from ``stages``,
  and per-stage engine gauges from ``engine_gauges``.  A nested
  ``loadgen`` SLO artifact (the live_mp_* rungs embed one) contributes
  its per-step series too.
- **loadgen SLO JSON** (``schema: mirbft-loadgen-slo/…``): per
  arrival-rate step, ``step.<name>.{goodput_per_sec,p50_ms,p95_ms,
  p99_ms,committed_reqs,…}`` — so a latency-SLO regression between two
  load runs gates exactly like a timeline regression (``duplicates``
  and ``timed_out`` are reported as informational).
- **capacity JSON** (``schema: mirbft-capacity/…``, or nested under a
  bench JSON's ``capacity`` key): per config,
  ``knee.<config>.knee_rate_per_sec`` (a knee moving *down* gates) and
  ``knee.<config>.p95_at_knee_ms``, plus the headline
  ``knee_rate_per_sec``.

Direction is inferred per series name: throughput-like series
(``per_sec``, ``rate``, ``count``, ``events``) regress when they *drop*;
latency-like series (``p50/p95/p99``, ``ms``, ``seconds``, ``wall``)
regress when they *rise*; anything else is reported but never gates.

Resource-leak gating: when artifact B carries leak verdicts (a bench
JSON with a ``soak.leak`` mapping, or a standalone ``mirbft-soak/…``
artifact), any metric whose verdict is ``growing`` is a
``leak_failures`` entry and fails the diff exactly like a p95
regression — RSS or on-disk growth gates PRs, not just speed.

Device-plane gating: a bench ``device`` section contributes
``device.<fn>.retraces`` (gated: retrace *growth* between rungs is a
regression) and kernel timing series, and three absolute failures —
a retrace-budget breach in B, any shadow-oracle divergence recorded in
B, or a nonzero ``soak.divergence`` count — land in
``device_failures`` and fail the diff regardless of A.

Recovery: ``load_artifact`` accepts either a bench summary JSON or a
``BENCH_stream.jsonl`` journal (auto-detected) — when the summary is
missing or torn (rc=124 runs), the journal's ``final`` line or, failing
that, its stage lines reconstruct the artifact, so the perf trajectory
is never empty.
"""

from __future__ import annotations

import json

from .timeline import TimelineProfiler

DEFAULT_THRESHOLD_PCT = 10.0

_HIGHER_BETTER = ("per_sec", "rate", "count", "events", "reqs", "verified")
_LOWER_BETTER = (
    "p50", "p95", "p99", "_ms", "ms_", "seconds", "wall", "sim_ms", "retrace",
)


def direction(name):
    """'higher', 'lower', or None (informational only)."""
    lowered = name.lower()
    if any(tok in lowered for tok in _HIGHER_BETTER):
        return "higher"
    if any(tok in lowered for tok in _LOWER_BETTER):
        return "lower"
    return None


def _loadgen_series(doc, prefix=""):
    """Per-step series from a ``mirbft-loadgen-slo`` artifact.  The
    ``committed`` count is exposed as ``committed_reqs`` so the
    direction rules read it as throughput-like; ``duplicates`` and
    ``timed_out`` match no direction token and stay informational."""
    series = {}
    for step in doc.get("steps") or []:
        base = f"{prefix}step.{step.get('name', 'step')}."
        for key, out in (
            ("offered_rate_per_sec", "offered_rate_per_sec"),
            ("goodput_per_sec", "goodput_per_sec"),
            ("p50_ms", "p50_ms"),
            ("p95_ms", "p95_ms"),
            ("p99_ms", "p99_ms"),
            ("committed", "committed_reqs"),
            ("duplicates", "duplicates"),
            ("timed_out", "timed_out"),
            # KV app-rung splits (present only in app workload artifacts);
            # the *_ms / goodput_per_sec suffixes reuse the existing
            # direction tokens, so these gate without new rules.
            ("read_p50_ms", "read_p50_ms"),
            ("read_p95_ms", "read_p95_ms"),
            ("read_p99_ms", "read_p99_ms"),
            ("write_p50_ms", "write_p50_ms"),
            ("write_p95_ms", "write_p95_ms"),
            ("write_p99_ms", "write_p99_ms"),
            ("read_goodput_per_sec", "read_goodput_per_sec"),
            ("write_goodput_per_sec", "write_goodput_per_sec"),
            ("reads_failed", "reads_failed"),
        ):
            value = step.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                series[base + out] = float(value)
    return series


def _capacity_series(doc, prefix=""):
    """Series from a ``mirbft-capacity`` artifact (loadgen/knee.py).

    ``knee_rate_per_sec`` carries the ``per_sec`` token, so a knee that
    moves down between artifacts gates as a regression exactly like a
    p95 rise; ``p95_at_knee_ms`` (the p95 of the highest passing step)
    gates lower-is-better.  A config whose knee was not located within
    budget contributes no knee series (absent, not zero — a located
    knee appearing later must not diff against a fake 0).
    """
    series = {}
    top = doc.get("knee_rate_per_sec")
    if isinstance(top, (int, float)) and not isinstance(top, bool):
        series[f"{prefix}knee_rate_per_sec"] = float(top)
    for config in doc.get("configs") or []:
        name = config.get("config", "config")
        knee = config.get("knee_rate_per_sec")
        if isinstance(knee, (int, float)) and not isinstance(knee, bool):
            series[f"{prefix}knee.{name}.knee_rate_per_sec"] = float(knee)
        passing = [
            s
            for s in config.get("steps") or []
            if s.get("ok") and isinstance(s.get("rate_per_sec"), (int, float))
        ]
        if passing:
            at_knee = max(passing, key=lambda s: s["rate_per_sec"])
            p95 = at_knee.get("p95_ms")
            if isinstance(p95, (int, float)) and not isinstance(p95, bool):
                series[f"{prefix}knee.{name}.p95_at_knee_ms"] = float(p95)
    return series


def extract_series(artifact):
    """Flatten one parsed artifact into ``{series_name: float}``."""
    if str(artifact.get("schema", "")).startswith("mirbft-loadgen-slo"):
        return _loadgen_series(artifact)
    if str(artifact.get("schema", "")).startswith("mirbft-capacity"):
        return _capacity_series(artifact)
    if "traceEvents" in artifact:
        profiler = TimelineProfiler.from_chrome_trace(artifact)
        series = {}
        for stats in profiler.stats():
            series[f"phase.{stats.phase}.count"] = float(stats.count)
            series[f"phase.{stats.phase}.p50_ms"] = stats.p50
            series[f"phase.{stats.phase}.p95_ms"] = stats.p95
            series[f"phase.{stats.phase}.p99_ms"] = stats.p99
        return series
    series = {}
    for key, value in artifact.items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            series[key] = float(value)
    for stage, info in (artifact.get("stages") or {}).items():
        seconds = (info or {}).get("seconds")
        if isinstance(seconds, (int, float)):
            series[f"stage.{stage}.seconds"] = float(seconds)
    for stage, gauges in (artifact.get("engine_gauges") or {}).items():
        for gauge, value in (gauges or {}).items():
            if isinstance(value, (int, float)):
                series[f"engine.{stage}.{gauge}"] = float(value)
    loadgen_doc = artifact.get("loadgen")
    if isinstance(loadgen_doc, dict):
        series.update(_loadgen_series(loadgen_doc, prefix="loadgen."))
    app_doc = artifact.get("loadgen_app")
    if isinstance(app_doc, dict):
        series.update(_loadgen_series(app_doc, prefix="loadgen_app."))
    capacity_doc = artifact.get("capacity")
    if isinstance(capacity_doc, dict):
        series.update(_capacity_series(capacity_doc, prefix="capacity."))
    device = artifact.get("device")
    if isinstance(device, dict):
        for fn, n in sorted((device.get("retraces") or {}).items()):
            if isinstance(n, (int, float)) and not isinstance(n, bool):
                series[f"device.{fn}.retraces"] = float(n)
        for kernel, info in sorted((device.get("kernel_seconds") or {}).items()):
            mean = (info or {}).get("mean_ms")
            if isinstance(mean, (int, float)) and not isinstance(mean, bool):
                series[f"device.{kernel}.mean_ms"] = float(mean)
            calls = (info or {}).get("count")
            if isinstance(calls, (int, float)) and not isinstance(calls, bool):
                # "calls" deliberately matches no direction token: launch
                # counts vary run-to-run and must not gate.
                series[f"device.{kernel}.calls"] = float(calls)
        for dirn, n in sorted((device.get("transfer_bytes") or {}).items()):
            if isinstance(n, (int, float)) and not isinstance(n, bool):
                series[f"device.transfer.{dirn}"] = float(n)
    for metric, verdict in sorted(extract_leaks(artifact).items()):
        for key in ("first", "last", "rel_pct_per_min"):
            value = verdict.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                series[f"soak.{metric}.{key}"] = float(value)
    return series


def extract_leaks(artifact):
    """``{metric: leak_verdict_dict}`` from a bench or soak artifact.

    Bench JSON nests the verdicts under ``soak.leak``; a standalone
    soak artifact (``schema: mirbft-soak/…``) carries ``leak`` at the
    top level.  Anything else yields an empty mapping.
    """
    if str(artifact.get("schema", "")).startswith("mirbft-soak"):
        leaks = artifact.get("leak") or {}
    else:
        soak = artifact.get("soak")
        leaks = (soak.get("leak") or {}) if isinstance(soak, dict) else {}
    return {
        name: verdict
        for name, verdict in leaks.items()
        if isinstance(verdict, dict)
    }


def diff_series(a, b, threshold_pct=DEFAULT_THRESHOLD_PCT):
    """Compare two series maps; returns the verdict dict.

    ``delta_pct`` is signed toward "worse": positive means B regressed
    relative to A by that percentage, regardless of direction.
    """
    regressions = []
    improvements = []
    unchanged = []
    informational = []
    for name in sorted(set(a) & set(b)):
        va, vb = a[name], b[name]
        dirn = direction(name)
        if va == vb:
            unchanged.append(name)
            continue
        if va == 0:
            # No baseline to take a percentage of; report, never gate.
            informational.append({"series": name, "a": va, "b": vb})
            continue
        raw_pct = (vb - va) / abs(va) * 100.0
        if dirn is None:
            informational.append(
                {"series": name, "a": va, "b": vb, "change_pct": raw_pct}
            )
            continue
        worse_pct = raw_pct if dirn == "lower" else -raw_pct
        entry = {
            "series": name,
            "a": va,
            "b": vb,
            "direction": dirn,
            "delta_pct": worse_pct,
        }
        if worse_pct >= threshold_pct:
            regressions.append(entry)
        else:
            improvements.append(entry)
    return {
        "threshold_pct": threshold_pct,
        "ok": not regressions,
        "regressions": regressions,
        "improvements": improvements,
        "unchanged": unchanged,
        "informational": informational,
        "only_a": sorted(set(a) - set(b)),
        "only_b": sorted(set(b) - set(a)),
    }


def recover_stream(path):
    """Reconstruct a bench artifact from a ``BENCH_stream.jsonl`` journal.

    The ``final`` line, when present, IS the artifact.  Otherwise (the
    run was killed mid-flight) the stage lines rebuild a reduced
    artifact — per-stage seconds/status/compile_s — under the schema
    ``mirbft-bench-recovered/1`` with ``recovered: true`` so consumers
    can tell a rescued rung from a clean one.  Torn trailing lines
    (SIGKILL mid-write) are skipped, not fatal.
    """
    header = None
    final = None
    stages = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from a killed run
            if not isinstance(rec, dict):
                continue
            kind = rec.get("kind")
            if kind == "header":
                header = rec
            elif kind == "stage":
                name = rec.get("stage", "?")
                stages[name] = {
                    k: v
                    for k, v in rec.items()
                    if k not in ("kind", "stage", "schema")
                }
            elif kind == "final" and isinstance(rec.get("payload"), dict):
                final = rec["payload"]
    if final is not None:
        return final
    doc = {
        "schema": "mirbft-bench-recovered/1",
        "recovered": True,
        "stages": stages,
    }
    if header is not None:
        doc["pid"] = header.get("pid")
    return doc


def load_artifact(path):
    """Load one artifact: a JSON document, or a bench-stream journal
    (``.jsonl`` — or any file whose body is line-JSON) recovered via
    :func:`recover_stream`."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        return recover_stream(path)
    if isinstance(doc, dict) and doc.get("kind") == "header" and str(
        doc.get("schema", "")
    ).startswith("mirbft-bench-stream"):
        # A one-line journal (header only, run died before any stage).
        return recover_stream(path)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict) and (
        "cmd" in doc and "rc" in doc
    ):
        # A committed BENCH_r*.json wrapper ({n, cmd, rc, tail, parsed}):
        # the bench payload lives under "parsed" — diff that, so the
        # PR-over-PR gate compares the actual series instead of the
        # wrapper's bookkeeping fields.
        return doc["parsed"]
    return doc


def diff_files(path_a, path_b, threshold_pct=DEFAULT_THRESHOLD_PCT):
    """Load, extract, and diff two artifact files (summary JSON or
    bench-stream journals — see :func:`load_artifact`)."""
    a = load_artifact(path_a)
    b = load_artifact(path_b)
    report = diff_series(
        extract_series(a), extract_series(b), threshold_pct=threshold_pct
    )
    apply_leak_gate(report, b)
    apply_device_gate(report, b)
    report["a"] = str(path_a)
    report["b"] = str(path_b)
    return report


def apply_leak_gate(report, artifact_b):
    """Fold B's leak verdicts into a diff report (in place).

    A ``growing`` verdict in the *new* artifact fails the gate
    regardless of what A looked like — a leak is absolute, not
    relative.  Verdicts from A are irrelevant: they gated A's own PR.
    """
    failures = []
    for metric, verdict in sorted(extract_leaks(artifact_b).items()):
        if verdict.get("verdict") == "growing":
            failures.append(
                {
                    "series": f"soak.{metric}",
                    "verdict": "growing",
                    "confidence": verdict.get("confidence"),
                    "rel_pct_per_min": verdict.get("rel_pct_per_min"),
                    "first": verdict.get("first"),
                    "last": verdict.get("last"),
                }
            )
    report["leak_failures"] = failures
    report["ok"] = report["ok"] and not failures
    return report


def apply_device_gate(report, artifact_b):
    """Fold B's device-plane verdicts into a diff report (in place).

    Absolute failures, like leaks: a retrace-budget breach or any
    recorded scalar/vector divergence in the *new* artifact fails the
    gate regardless of A."""
    failures = []
    device = artifact_b.get("device")
    if isinstance(device, dict):
        budget = device.get("retrace_budget")
        retraces = device.get("retraces") or {}
        for fn in device.get("retrace_breaches") or ():
            failures.append(
                {
                    "series": f"device.{fn}.retraces",
                    "kind": "retrace_budget",
                    "count": retraces.get(fn),
                    "budget": budget,
                }
            )
        total = device.get("divergence_total")
        if isinstance(total, (int, float)) and total > 0:
            failures.append(
                {
                    "series": "device.divergence_total",
                    "kind": "divergence",
                    "count": total,
                }
            )
    soak = artifact_b.get("soak")
    if isinstance(soak, dict):
        div = soak.get("divergence")
        if isinstance(div, (int, float)) and div > 0:
            failures.append(
                {
                    "series": "soak.divergence",
                    "kind": "divergence",
                    "count": div,
                }
            )
    report["device_failures"] = failures
    report["ok"] = report["ok"] and not failures
    return report


def render_report(report):
    """Human-readable summary lines for the CLI."""
    lines = [
        f"diff {report.get('a', 'A')} -> {report.get('b', 'B')} "
        f"(threshold {report['threshold_pct']:g}%)"
    ]
    for entry in report["regressions"]:
        lines.append(
            f"  REGRESSED {entry['series']}: {entry['a']:g} -> {entry['b']:g} "
            f"({entry['delta_pct']:+.1f}% worse)"
        )
    for entry in report["improvements"]:
        lines.append(
            f"  ok        {entry['series']}: {entry['a']:g} -> {entry['b']:g} "
            f"({entry['delta_pct']:+.1f}% worse)"
        )
    for entry in report.get("leak_failures", ()):
        lines.append(
            f"  LEAK      {entry['series']}: {entry['first']:g} -> "
            f"{entry['last']:g} ({entry['rel_pct_per_min']:+.1f}%/min, "
            f"confidence {entry['confidence']:.2f})"
        )
    for entry in report.get("device_failures", ()):
        if entry["kind"] == "retrace_budget":
            lines.append(
                f"  DEVICE    {entry['series']}: {entry['count']} retraces "
                f"(budget {entry['budget']})"
            )
        else:
            lines.append(
                f"  DEVICE    {entry['series']}: {entry['count']} "
                "scalar/vector divergence(s)"
            )
    lines.append(
        f"  unchanged: {len(report['unchanged'])}  "
        f"informational: {len(report['informational'])}  "
        f"only-in-one: {len(report['only_a']) + len(report['only_b'])}"
    )
    verdict = "ok"
    if report["regressions"]:
        verdict = "REGRESSION"
    elif report.get("leak_failures"):
        verdict = "LEAK"
    elif report.get("device_failures"):
        verdict = "DEVICE"
    lines.append("VERDICT: " + verdict)
    return "\n".join(lines)
