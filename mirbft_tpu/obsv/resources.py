"""Process resource sampling and least-squares leak verdicts.

This module is the single sanctioned home for process introspection
(lint rule W14): RSS, open file descriptors, thread counts, and
on-disk store footprints are sampled here and nowhere else.  Samplers
feed catalog-registered gauges (``mirbft_resource_*``) and, when a
flight recorder is wired, periodic ``resource`` snapshots into its
ring buffer.

Everything is stdlib-only: ``psutil`` is deliberately not used (it is
not part of the pinned environment), so the samplers read
``/proc/self`` directly and degrade to ``None`` where the platform
does not expose a number.

``leak_verdict`` turns a sampled series into a ``flat``/``growing``
verdict via an ordinary least-squares slope, normalised to percent of
the series mean per minute so the same threshold works for bytes,
fds, and thread counts.  ``obsv --diff`` and the bench soak rung gate
on the verdict the same way they gate on p95 regressions.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "rss_bytes",
    "open_fds",
    "thread_count",
    "dir_bytes",
    "sample_process",
    "leak_verdict",
    "ResourceSampler",
]

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes():
    """Current resident set size in bytes, or None when unreadable.

    ``/proc/self/statm`` reports *current* pages; ``getrusage`` only
    reports the high-water mark, which can never shrink and would make
    every leak series look monotone.  The peak is used only as a
    last-resort fallback on /proc-less platforms.
    """
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as fh:
            fields = fh.read().split()
        return int(fields[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource as _resource

        peak_kb = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
        return int(peak_kb) * 1024
    except Exception:
        return None


def open_fds():
    """Number of open file descriptors, or None when unreadable."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def thread_count():
    """Live Python threads in this process."""
    return threading.active_count()


def dir_bytes(path):
    """Total size of regular files under ``path`` (0 if absent).

    Races with concurrent segment rotation are expected: a file listed
    by the walk may vanish before stat, which counts as zero rather
    than raising.
    """
    total = 0
    if not path:
        return 0
    try:
        for root, _dirs, files in os.walk(path):
            for name in files:
                try:
                    total += os.stat(os.path.join(root, name)).st_size
                except OSError:
                    continue
    except OSError:
        return total
    return total


def sample_process(dirs=None):
    """One snapshot of the process: rss/fds/threads plus named dirs.

    ``dirs`` maps a store label (e.g. ``"wal"``) to a directory path;
    each contributes a ``disk.<label>`` entry in the returned dict.
    ``None`` values mark metrics the platform could not provide.
    """
    sample = {
        "rss_bytes": rss_bytes(),
        "open_fds": open_fds(),
        "threads": thread_count(),
    }
    for label, path in sorted((dirs or {}).items()):
        sample[f"disk.{label}"] = dir_bytes(path)
    return sample


def _least_squares(samples):
    """Slope/intercept/r^2 of (t, v) pairs; None when degenerate."""
    n = len(samples)
    if n < 2:
        return None
    mean_t = sum(t for t, _ in samples) / n
    mean_v = sum(v for _, v in samples) / n
    var_t = sum((t - mean_t) ** 2 for t, _ in samples)
    if var_t <= 0.0:
        return None
    cov = sum((t - mean_t) * (v - mean_v) for t, v in samples)
    slope = cov / var_t
    var_v = sum((v - mean_v) ** 2 for _, v in samples)
    if var_v <= 0.0:
        r2 = 0.0
    else:
        r2 = (cov * cov) / (var_t * var_v)
    return slope, mean_v, r2


def leak_verdict(
    samples,
    threshold_pct_per_min=5.0,
    min_samples=8,
    min_r2=0.5,
):
    """Classify a sampled series as ``flat`` or ``growing``.

    ``samples`` is a sequence of ``(t_seconds, value)`` pairs.  The
    verdict is ``growing`` only when the least-squares slope exceeds
    ``threshold_pct_per_min`` percent of the series mean per minute
    AND the fit explains the data (r^2 >= ``min_r2``) AND there are at
    least ``min_samples`` points — noisy or short series stay ``flat``
    with low confidence rather than flapping a PR gate.

    Returns a dict with the verdict, a 0..1 confidence, the raw and
    normalised slopes, the fit quality, and series endpoints, shaped
    for direct embedding in bench/soak JSON artifacts.
    """
    pts = [(float(t), float(v)) for t, v in samples if v is not None]
    base = {
        "verdict": "flat",
        "confidence": 0.0,
        "slope_per_s": 0.0,
        "rel_pct_per_min": 0.0,
        "r2": 0.0,
        "n": len(pts),
        "first": pts[0][1] if pts else None,
        "last": pts[-1][1] if pts else None,
        "mean": None,
        "span_s": (pts[-1][0] - pts[0][0]) if len(pts) >= 2 else 0.0,
    }
    if len(pts) < 2:
        return base
    fit = _least_squares(pts)
    mean_v = sum(v for _, v in pts) / len(pts)
    base["mean"] = mean_v
    if fit is None:
        return base
    slope, _, r2 = fit
    base["slope_per_s"] = slope
    base["r2"] = r2
    if mean_v:
        rel = (slope * 60.0 / abs(mean_v)) * 100.0
    elif slope > 0:
        rel = float("inf")
    else:
        rel = 0.0
    base["rel_pct_per_min"] = rel
    var_v = sum((v - mean_v) ** 2 for _, v in pts)
    if var_v <= 0.0:
        # Perfectly constant series: the strongest possible "flat".
        base["confidence"] = 1.0
        return base
    growing = (
        rel > threshold_pct_per_min
        and r2 >= min_r2
        and len(pts) >= min_samples
    )
    if growing:
        base["verdict"] = "growing"
        base["confidence"] = r2
    else:
        # Two independent ways a series is convincingly flat: a steep
        # nominal slope the fit cannot explain (sawtooth around a steady
        # mean — disk between compactions — has rel >> threshold but
        # r^2 ~ 0), or a well-fit slope far under the threshold.  Take
        # the stronger signal.
        base["confidence"] = max(
            0.0,
            min(
                1.0,
                max(
                    1.0 - r2,
                    1.0 - max(rel, 0.0) / threshold_pct_per_min,
                ),
            ),
        )
    return base


class ResourceSampler:
    """Background thread sampling process resources on an interval.

    Each tick feeds catalog gauges (when a registry is supplied),
    optionally a flight recorder (``resource`` entries), and an
    in-memory ``(t, v)`` series per metric for ``verdicts()``.
    ``dirs`` maps store labels to directories whose on-disk bytes are
    tracked (``mirbft_resource_disk_bytes{store=...}``).
    """

    def __init__(
        self,
        registry=None,
        recorder=None,
        interval_s=0.5,
        dirs=None,
        node="proc",
    ):
        self.registry = registry
        self.recorder = recorder
        self.interval_s = max(0.05, float(interval_s))
        self.dirs = dict(dirs or {})
        self.node = node
        self.series = {}
        self._t0 = time.perf_counter()
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    def sample_once(self):
        """Take one sample; safe to call without start() (tests)."""
        now = time.perf_counter() - self._t0
        sample = sample_process(self.dirs)
        # Device-plane gauges ride the same cadence; memory_sample() is
        # None-safe (no jax imported, or the backend raced away).
        from .device import memory_sample

        device = memory_sample()
        if device is not None:
            for key, value in device.items():
                sample["device." + key] = value
        with self._lock:
            for name, value in sample.items():
                if value is None:
                    continue
                self.series.setdefault(name, []).append((now, value))
        if self.registry is not None:
            gauges = {
                "rss_bytes": "mirbft_resource_rss_bytes",
                "open_fds": "mirbft_resource_open_fds",
                "threads": "mirbft_resource_threads",
                "device.live_buffers": "mirbft_device_live_buffers",
                "device.live_buffer_bytes": "mirbft_device_live_buffer_bytes",
                "device.hbm_bytes": "mirbft_device_hbm_bytes",
            }
            for key, metric in gauges.items():
                if sample.get(key) is not None:
                    self.registry.gauge(metric).set(sample[key])
            for name, value in sample.items():
                if name.startswith("disk.") and value is not None:
                    self.registry.gauge(
                        "mirbft_resource_disk_bytes",
                        store=name[len("disk."):],
                    ).set(value)
        if self.recorder is not None:
            self.recorder.record(
                "resource",
                "resource.sample",
                self.node,
                {k: v for k, v in sample.items() if v is not None},
            )
        return sample

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # A failed tick (e.g. /proc raced away) must not kill
                # the sampler for the rest of the soak.
                continue

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="obsv-resource-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def snapshot_series(self):
        with self._lock:
            return {name: list(pts) for name, pts in self.series.items()}

    def verdicts(self, **kwargs):
        """Leak verdict per sampled metric family.

        ``device.*`` series are sampled and recorded but excluded from
        the leak fit: live-buffer counts track jit-cache churn, not
        process growth, and a growing-verdict there would gate PRs on
        compiler behavior."""
        return {
            name: leak_verdict(pts, **kwargs)
            for name, pts in sorted(self.snapshot_series().items())
            if not name.startswith("device.")
        }
