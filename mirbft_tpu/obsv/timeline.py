"""Consensus timeline profiler.

Reconstructs the per-(node, seq) lifecycle from the protocol-milestone
instant events the instrumented core emits —

    seq.allocated      batch allocated to a sequence (request arrival
                       at the consensus layer)
    seq.preprepared    digest verified, preprepare applied
    seq.prepared       prepare quorum reached
    seq.commit_quorum  commit quorum reached (state COMMITTED)
    seq.committed      batch applied to the node's log
    ckpt.stable        checkpoint covering the seq went stable

— and emits p50/p95/p99 per protocol phase:

    preprepare   seq.allocated      -> seq.preprepared
    prepare      seq.preprepared    -> seq.prepared
    commit       seq.prepared       -> seq.commit_quorum
    checkpoint   seq.commit_quorum  -> first ckpt.stable with
                 checkpoint seq >= seq at the same node

Under the testengine every milestone carries ``args.sim_ms`` (the
Recorder's simulated clock), and the profiler prefers it — phase
durations are then deterministic simulated milliseconds.  Without it
(runtime spans) it falls back to the monotonic wall timestamp (``ts``,
microseconds, converted to ms).
"""

from __future__ import annotations

from dataclasses import dataclass

PHASES = ("preprepare", "prepare", "commit", "checkpoint")

_PHASE_EDGES = {
    "preprepare": ("seq.allocated", "seq.preprepared"),
    "prepare": ("seq.preprepared", "seq.prepared"),
    "commit": ("seq.prepared", "seq.commit_quorum"),
}

_MILESTONES = frozenset(
    name for edge in _PHASE_EDGES.values() for name in edge
) | {"seq.committed"}


@dataclass
class PhaseStats:
    phase: str
    count: int
    p50: float
    p95: float
    p99: float


def _percentile(sorted_samples, q):
    """Nearest-rank percentile on a pre-sorted list."""
    n = len(sorted_samples)
    return sorted_samples[min(n - 1, int(q * n))]


class TimelineProfiler:
    """Feed it milestone instants, ask for per-phase latency stats."""

    def __init__(self):
        # (node, seq) -> {milestone name -> time_ms}
        self._marks = {}
        # node -> [(ckpt_seq, time_ms)] in arrival order
        self._ckpts = {}

    @staticmethod
    def _event_time_ms(event):
        args = event.get("args") or {}
        sim = args.get("sim_ms")
        if sim is not None:
            return float(sim)
        return event.get("ts", 0.0) / 1000.0

    def add_event(self, event):
        if event.get("ph") != "i":
            return
        name = event.get("name", "")
        args = event.get("args") or {}
        node = args.get("node")
        seq = args.get("seq")
        if node is None or seq is None:
            return
        t = self._event_time_ms(event)
        if name in _MILESTONES:
            self._marks.setdefault((node, seq), {}).setdefault(name, t)
        elif name == "ckpt.stable":
            self._ckpts.setdefault(node, []).append((seq, t))

    @classmethod
    def from_events(cls, events):
        profiler = cls()
        for event in events:
            profiler.add_event(event)
        return profiler

    @classmethod
    def from_tracer(cls, tracer):
        return cls.from_events(tracer.events)

    @classmethod
    def from_chrome_trace(cls, trace):
        """``trace`` is the loaded JSON object ({"traceEvents": [...]})."""
        return cls.from_events(trace.get("traceEvents", ()))

    def phase_samples(self):
        """phase -> list of duration samples (ms)."""
        samples = {phase: [] for phase in PHASES}
        for (node, seq), marks in self._marks.items():
            for phase, (start, end) in _PHASE_EDGES.items():
                if start in marks and end in marks:
                    samples[phase].append(marks[end] - marks[start])
            cq = marks.get("seq.commit_quorum")
            if cq is not None:
                stable = self._first_stable_after(node, seq, cq)
                if stable is not None:
                    samples["checkpoint"].append(stable - cq)
        return samples

    def _first_stable_after(self, node, seq, not_before):
        best = None
        for ckpt_seq, t in self._ckpts.get(node, ()):
            if ckpt_seq >= seq and t >= not_before:
                if best is None or t < best:
                    best = t
        return best

    def stats(self):
        """[PhaseStats] for phases that collected at least one sample."""
        out = []
        all_samples = self.phase_samples()
        for phase in PHASES:
            samples = sorted(all_samples[phase])
            if not samples:
                continue
            out.append(
                PhaseStats(
                    phase=phase,
                    count=len(samples),
                    p50=_percentile(samples, 0.50),
                    p95=_percentile(samples, 0.95),
                    p99=_percentile(samples, 0.99),
                )
            )
        return out

    def table(self):
        """ASCII latency table (ms) for the CLI."""
        rows = self.stats()
        lines = [
            f"{'phase':<12} {'count':>7} {'p50_ms':>10} "
            f"{'p95_ms':>10} {'p99_ms':>10}",
            "-" * 53,
        ]
        if not rows:
            lines.append("(no milestone events collected)")
        for s in rows:
            lines.append(
                f"{s.phase:<12} {s.count:>7} {s.p50:>10.3f} "
                f"{s.p95:>10.3f} {s.p99:>10.3f}"
            )
        return "\n".join(lines)
