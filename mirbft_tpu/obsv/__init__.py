"""Observability plane: metrics registry, trace spans, timeline profiler,
cross-node flow merging, HTTP exposition, and a timeline-diff gate.

Usage:

    from mirbft_tpu.obsv import hooks
    registry, tracer = hooks.enable(trace=True)
    ...  # run instrumented code
    print(registry.prometheus_text())
    tracer.write("/tmp/trace.json")  # open in ui.perfetto.dev
    hooks.disable()

Instrumented call sites across core/testengine/runtime/chaos guard on
``hooks.enabled`` so that with observability off the entire plane costs
one branch per boundary crossing.  ``python -m mirbft_tpu.obsv`` runs an
instrumented testengine ladder and prints the per-phase consensus
latency table; ``--merge`` combines per-node traces and ``--diff`` gates
one artifact against another.
"""

from __future__ import annotations

from . import hooks
from .bqueue import BoundedQueue, QueueTelemetry
from .critpath import FlowRecord, attribute, attribution_table, build_ledger
from .diff import diff_files, diff_series, extract_series
from .exporter import ObsvExporter
from .merge import aligned_events, merge_files, merge_traces, split_node_traces
from .metrics import (
    CARDINALITY,
    CATALOG,
    CATALOG_LABELS,
    DEFAULT_BUCKETS,
    DEFAULT_CARDINALITY,
    CardinalityError,
    NullRegistry,
    Registry,
    null_registry,
)
from .timeline import PHASES, PhaseStats, TimelineProfiler
from .trace import SpanSampler, Tracer

__all__ = [
    "BoundedQueue",
    "CARDINALITY",
    "CATALOG",
    "CATALOG_LABELS",
    "CardinalityError",
    "DEFAULT_BUCKETS",
    "DEFAULT_CARDINALITY",
    "FlowRecord",
    "NullRegistry",
    "ObsvExporter",
    "PHASES",
    "PhaseStats",
    "QueueTelemetry",
    "Registry",
    "SpanSampler",
    "TimelineProfiler",
    "Tracer",
    "aligned_events",
    "attribute",
    "attribution_table",
    "build_ledger",
    "diff_files",
    "diff_series",
    "extract_series",
    "hooks",
    "merge_files",
    "merge_traces",
    "null_registry",
    "split_node_traces",
]
