"""Observability plane: metrics registry, trace spans, timeline profiler.

Usage:

    from mirbft_tpu.obsv import hooks
    registry, tracer = hooks.enable(trace=True)
    ...  # run instrumented code
    print(registry.prometheus_text())
    tracer.write("/tmp/trace.json")  # open in ui.perfetto.dev
    hooks.disable()

Instrumented call sites across core/testengine/runtime/chaos guard on
``hooks.enabled`` so that with observability off the entire plane costs
one branch per boundary crossing.  ``python -m mirbft_tpu.obsv`` runs an
instrumented testengine ladder and prints the per-phase consensus
latency table.
"""

from __future__ import annotations

from . import hooks
from .metrics import (
    CATALOG,
    DEFAULT_BUCKETS,
    NullRegistry,
    Registry,
    null_registry,
)
from .timeline import PHASES, PhaseStats, TimelineProfiler
from .trace import Tracer

__all__ = [
    "CATALOG",
    "DEFAULT_BUCKETS",
    "NullRegistry",
    "PHASES",
    "PhaseStats",
    "Registry",
    "TimelineProfiler",
    "Tracer",
    "hooks",
    "null_registry",
]
