"""Trace spans with monotonic timestamps and Chrome trace-event export.

Events accumulate in memory as plain dicts already shaped like Chrome
trace-event JSON (the ``traceEvents`` array format), so ``write()`` is a
single ``json.dump``.  Load the output in Perfetto (ui.perfetto.dev) or
``chrome://tracing``.

Event vocabulary used here:

- ``ph: "X"`` complete events — one span with ``ts``/``dur`` in
  microseconds.  Emitted at span *close*, which is why per-tid nesting is
  reconstructed from interval containment, not emission order.
- ``ph: "i"`` instant events with scope ``"t"`` (thread) — protocol
  milestones (``seq.preprepared`` etc.), carrying ``args`` including the
  simulated clock when the testengine is driving.
- ``ph: "M"`` metadata — thread names, so Perfetto rows read "node 0"
  instead of bare tids.

All timestamps come from ``time.perf_counter_ns`` relative to the
tracer's birth — monotonic by construction (W7 lint forbids
``time.time`` here).
"""

from __future__ import annotations

import json
import time


class _Span:
    """Context manager handle; created by Tracer.span()."""

    __slots__ = ("_tracer", "_name", "_cat", "_tid", "_args", "_start_ns")

    def __init__(self, tracer, name, cat, tid, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._tid = tid
        self._args = args
        self._start_ns = 0

    def __enter__(self):
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._complete_ns(
            self._name,
            self._cat,
            self._tid,
            self._start_ns,
            time.perf_counter_ns(),
            self._args,
        )
        return False


class Tracer:
    """In-memory Chrome trace-event collector.

    Not thread-safe per event list mutation beyond CPython's list.append
    atomicity — which is exactly what the runtime's pool lanes need, and
    the testengine is single-threaded anyway.
    """

    def __init__(self):
        self._t0_ns = time.perf_counter_ns()
        self.events = []
        self._thread_names = {}

    def _now_us(self):
        return (time.perf_counter_ns() - self._t0_ns) / 1000.0

    def name_thread(self, tid, name):
        """Label a tid (Perfetto row name); idempotent."""
        if self._thread_names.get(tid) != name:
            self._thread_names[tid] = name

    def span(self, name, cat="", tid=0, **args):
        """Context manager producing one ph:"X" complete event."""
        return _Span(self, name, cat, tid, args or None)

    def _complete_ns(self, name, cat, tid, start_ns, end_ns, args):
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "pid": 0,
            "tid": tid,
            "ts": (start_ns - self._t0_ns) / 1000.0,
            "dur": max(0.0, (end_ns - start_ns) / 1000.0),
        }
        if args:
            event["args"] = args
        self.events.append(event)

    def complete(self, name, cat="", tid=0, dur_s=0.0, args=None):
        """Record an already-measured span ending now (duration dur_s).
        The start is clamped to the tracer's birth so ``ts`` stays
        non-negative (Chrome trace validity) even for a span measured
        before the tracer existed."""
        end_ns = time.perf_counter_ns()
        start_ns = max(end_ns - int(dur_s * 1e9), self._t0_ns)
        self._complete_ns(name, cat, tid, start_ns, end_ns, args)

    def instant(self, name, cat="", tid=0, args=None):
        """Record a ph:"i" thread-scoped instant event."""
        event = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "pid": 0,
            "tid": tid,
            "ts": self._now_us(),
        }
        if args:
            event["args"] = args
        self.events.append(event)

    def chrome_trace(self):
        """The full trace as a Chrome trace-event JSON object."""
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 0,
                "tid": tid,
                "args": {"name": name},
            }
            for tid, name in sorted(self._thread_names.items())
        ]
        return {"traceEvents": meta + self.events}

    def write(self, path):
        """Serialize to ``path`` as Perfetto-loadable JSON."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)
