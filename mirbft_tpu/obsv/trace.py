"""Trace spans with monotonic timestamps and Chrome trace-event export.

Events accumulate in memory as plain dicts already shaped like Chrome
trace-event JSON (the ``traceEvents`` array format), so ``write()`` is a
single ``json.dump``.  Load the output in Perfetto (ui.perfetto.dev) or
``chrome://tracing``.

Event vocabulary used here:

- ``ph: "X"`` complete events — one span with ``ts``/``dur`` in
  microseconds.  Emitted at span *close*, which is why per-tid nesting is
  reconstructed from interval containment, not emission order.
- ``ph: "i"`` instant events with scope ``"t"`` (thread) — protocol
  milestones (``seq.preprepared`` etc.), carrying ``args`` including the
  simulated clock when the testengine is driving.
- ``ph: "s"/"t"/"f"`` flow events — one flow per committed sequence,
  id ``"<epoch>.<seq_no>.<bucket>"``, opened at ``seq.allocated``,
  stepped at each intermediate milestone, finished at ``seq.committed``.
  ``obsv/merge.py`` stitches these across per-node traces.
- ``ph: "M"`` metadata — thread names plus an optional ``clock_sync``
  record (monotonic anchor + peer offsets) that merge.py uses to align
  traces from different processes.

All timestamps come from ``time.perf_counter_ns`` relative to the
tracer's birth — monotonic by construction (W7 lint forbids
``time.time`` here).
"""

from __future__ import annotations

import json
import time

# Milestone names that participate in a sequence's flow.  Terminal
# milestones close the flow (ph "f"); the first milestone seen for a
# (tid, seq) opens it (ph "s"); anything in between is a step (ph "t").
FLOW_TERMINAL = frozenset({"seq.committed"})

#: Metadata record name carrying the tracer's monotonic anchor.
CLOCK_SYNC = "clock_sync"

#: Open-flow table bound.  Flows whose request dies before a terminal
#: milestone (censored under chaos, dropped by backpressure) would
#: otherwise pin their table entry forever; past this many open flows
#: the oldest is evicted and counted as abandoned.
MAX_OPEN_FLOWS = 4096


class SpanSampler:
    """Deterministic 1-in-k span sampling.

    ``rate`` is the target fraction of spans to keep; the stride is
    ``round(1/rate)``.  The phase within the stride is derived from
    ``seed`` so two tracers with the same seed keep the same spans —
    no wall clock, no ``random`` (W7-compatible).  Milestones and flow
    records are never routed through the sampler.
    """

    __slots__ = ("rate", "stride", "_n")

    def __init__(self, rate: float, seed: int = 0):
        if not (0.0 < rate <= 1.0):
            raise ValueError(f"sample rate must be in (0, 1], got {rate}")
        self.rate = rate
        self.stride = max(1, round(1.0 / rate))
        self._n = seed % self.stride

    def keep(self) -> bool:
        k = self._n == 0
        self._n += 1
        if self._n >= self.stride:
            self._n = 0
        return k


class _NullSpan:
    """Stand-in for a sampled-out span; records nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager handle; created by Tracer.span()."""

    __slots__ = ("_tracer", "_name", "_cat", "_tid", "_args", "_start_ns")

    def __init__(self, tracer, name, cat, tid, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._tid = tid
        self._args = args
        self._start_ns = 0

    def __enter__(self):
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._complete_ns(
            self._name,
            self._cat,
            self._tid,
            self._start_ns,
            time.perf_counter_ns(),
            self._args,
        )
        return False


class Tracer:
    """In-memory Chrome trace-event collector.

    Not thread-safe per event list mutation beyond CPython's list.append
    atomicity — which is exactly what the runtime's pool lanes need, and
    the testengine is single-threaded anyway.
    """

    def __init__(
        self,
        sampler: SpanSampler | None = None,
        max_open_flows: int = MAX_OPEN_FLOWS,
    ):
        self._t0_ns = time.perf_counter_ns()
        self.events = []
        self._thread_names = {}
        self._sampler = sampler
        # Open flows keyed by (tid, seq_no) -> flow id string.  The
        # terminal milestone site (engine apply / runtime commit) does
        # not know epoch/bucket, so it resolves the id here.  Bounded:
        # flows that never reach a terminal milestone are evicted
        # oldest-first past max_open_flows (dict insertion order is the
        # open order) and counted in ``abandoned_flows``.
        self._flows = {}
        self._max_open_flows = max(1, max_open_flows)
        self.abandoned_flows = 0
        self._clock_sync = None

    @property
    def t0_ns(self) -> int:
        """Monotonic birth anchor (perf_counter_ns at construction)."""
        return self._t0_ns

    def _now_us(self):
        return (time.perf_counter_ns() - self._t0_ns) / 1000.0

    def name_thread(self, tid, name):
        """Label a tid (Perfetto row name); idempotent."""
        if self._thread_names.get(tid) != name:
            self._thread_names[tid] = name

    def set_clock_sync(self, node, offsets_ns=None):
        """Attach a clock_sync metadata record to this trace.

        ``node`` is this trace's node id; ``offsets_ns`` maps peer node
        id -> (local monotonic - peer monotonic) in nanoseconds, as
        estimated from the transport hello handshake.  merge.py uses the
        reference node's offsets to shift peer lanes onto one timeline.
        """
        self._clock_sync = {
            "node": node,
            "t0_ns": self._t0_ns,
            "offsets_ns": {str(k): int(v) for k, v in (offsets_ns or {}).items()},
        }

    def span(self, name, cat="", tid=0, **args):
        """Context manager producing one ph:"X" complete event."""
        if self._sampler is not None and not self._sampler.keep():
            return _NULL_SPAN
        return _Span(self, name, cat, tid, args or None)

    def _complete_ns(self, name, cat, tid, start_ns, end_ns, args):
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "pid": 0,
            "tid": tid,
            "ts": (start_ns - self._t0_ns) / 1000.0,
            "dur": max(0.0, (end_ns - start_ns) / 1000.0),
        }
        if args:
            event["args"] = args
        self.events.append(event)

    def complete(self, name, cat="", tid=0, dur_s=0.0, args=None):
        """Record an already-measured span ending now (duration dur_s).
        The start is clamped to the tracer's birth so ``ts`` stays
        non-negative (Chrome trace validity) even for a span measured
        before the tracer existed."""
        if self._sampler is not None and not self._sampler.keep():
            return
        end_ns = time.perf_counter_ns()
        start_ns = max(end_ns - int(dur_s * 1e9), self._t0_ns)
        self._complete_ns(name, cat, tid, start_ns, end_ns, args)

    def instant(self, name, cat="", tid=0, args=None):
        """Record a ph:"i" thread-scoped instant event.

        Never sampled: milestones are the protocol's skeleton and the
        timeline profiler needs every one of them.
        """
        event = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "pid": 0,
            "tid": tid,
            "ts": self._now_us(),
        }
        if args:
            event["args"] = args
        self.events.append(event)

    def flow_milestone(self, name, tid, seq_no, epoch=None, bucket=None):
        """Record the flow event for one consensus milestone.

        The first milestone seen for ``(tid, seq_no)`` opens the flow
        (ph "s") — this requires epoch and bucket to mint the stable id
        ``"<epoch>.<seq_no>.<bucket>"``; without them the open is
        skipped and the whole flow stays silent for that tid.  Later
        milestones resolve the id from the open-flow table, so terminal
        sites need only the seq_no.  Never sampled.
        """
        key = (tid, seq_no)
        flow_id = self._flows.get(key)
        if flow_id is None:
            if epoch is None or bucket is None:
                return
            if len(self._flows) >= self._max_open_flows:
                self._evict_oldest_flow()
            flow_id = f"{epoch}.{seq_no}.{bucket}"
            self._flows[key] = flow_id
            ph = "s"
        elif name in FLOW_TERMINAL:
            del self._flows[key]
            ph = "f"
        else:
            ph = "t"
        event = {
            "name": name,
            "cat": "flow",
            "ph": ph,
            "id": flow_id,
            "pid": 0,
            "tid": tid,
            "ts": self._now_us(),
        }
        if ph == "f":
            # Bind to the enclosing slice's end rather than the next one.
            event["bp"] = "e"
        self.events.append(event)

    def _evict_oldest_flow(self):
        """Drop the oldest open flow (no terminal milestone ever came:
        the request was censored or dropped).  Counted both on the
        tracer and — when a registry is live — as
        ``mirbft_flow_abandoned_total`` so chaos runs can see censoring
        pressure without parsing the trace."""
        self._flows.pop(next(iter(self._flows)))
        self.abandoned_flows += 1
        from . import hooks  # local: trace is imported before hooks wires up

        registry = hooks.metrics
        if hooks.enabled and registry is not None:
            registry.counter("mirbft_flow_abandoned_total").inc()

    def flow_step(self, name, tid, flow_id):
        """Freestanding ph:"t" flow record with an explicit id.

        Used for milestone families without an open/close pair on one
        node (checkpoints: each node emits one ``ckpt.stable``); merge.py
        promotes the earliest/latest record per id to "s"/"f" so the
        merged trace stays well-formed.  Never sampled.
        """
        self.events.append(
            {
                "name": name,
                "cat": "flow",
                "ph": "t",
                "id": flow_id,
                "pid": 0,
                "tid": tid,
                "ts": self._now_us(),
            }
        )

    def chrome_trace(self):
        """The full trace as a Chrome trace-event JSON object."""
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 0,
                "tid": tid,
                "args": {"name": name},
            }
            for tid, name in sorted(self._thread_names.items())
        ]
        if self._clock_sync is not None:
            meta.append(
                {
                    "name": CLOCK_SYNC,
                    "ph": "M",
                    "pid": 0,
                    "tid": 0,
                    "args": dict(self._clock_sync),
                }
            )
        return {"traceEvents": meta + self.events}

    def write(self, path):
        """Serialize to ``path`` as Perfetto-loadable JSON."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)
