"""Merge N per-node Chrome traces into one Perfetto-loadable timeline.

Each input trace is one node's view of the run: milestone instants, flow
records (``ph: "s"/"t"/"f"`` keyed by the stable id
``"<epoch>.<seq_no>.<bucket>"``), and whatever spans the node captured.
The merge gives every node its own Perfetto *process* lane (``pid`` =
node id, named via ``process_name`` metadata) and aligns timestamps
using each trace's ``clock_sync`` metadata:

- ``t0_ns`` — the tracer's monotonic birth anchor.  Event ``ts`` values
  are microseconds relative to it, so the absolute monotonic time of an
  event is ``t0_ns + ts * 1000``.
- ``offsets_ns`` — peer id -> (reference clock - peer clock), estimated
  at handshake time.  The TCP transport exchanges ``perf_counter_ns``
  anchors in its hello frame; the testengine's nodes share one process
  clock so its offsets are zero (the alignment path still runs, it is
  just the identity).

Caveats (documented in docs/OBSERVABILITY.md): offsets estimated from a
one-way hello absorb the network latency of that hello, so cross-host
alignment is accurate to ~one-way-latency; on a single host all
processes share CLOCK_MONOTONIC and alignment is exact.

Flow hygiene: per flow id the merge keeps the earliest ``s`` and the
latest ``f`` and demotes duplicates to ``t`` (every node opens its own
view of a sequence's flow, but a merged flow must have exactly one
start/finish).  Ids seen only as steps (checkpoint flows) are promoted —
earliest record becomes ``s``, latest ``f`` — and ids with a single
record are dropped.  Finally, a 1 µs anchor slice is synthesized under
each flow record so Perfetto has a slice to bind the arrows to
(flow events attach to slices, not instants).
"""

from __future__ import annotations

import json

from .trace import CLOCK_SYNC

_FLOW_PHS = ("s", "t", "f")


def split_node_traces(tracer, nodes):
    """Split one testengine tracer into per-node Chrome trace objects.

    The testengine drives every node in one process with one tracer,
    keying milestones by ``tid`` = node id.  This produces the N
    per-node trace files a real deployment would write, each carrying a
    ``clock_sync`` anchor (shared ``t0_ns``, zero offsets — handshake
    estimation against yourself) so the merge path is identical for
    simulated and TCP runs.  Events on non-node tids (process-wide
    crypto/flush spans) are not attributable to one node and are left
    out.
    """
    node_set = set(nodes)
    out = {}
    for node in nodes:
        out[node] = {
            "traceEvents": [
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 0,
                    "tid": node,
                    "args": {"name": f"node {node}"},
                },
                {
                    "name": CLOCK_SYNC,
                    "ph": "M",
                    "pid": 0,
                    "tid": 0,
                    "args": {
                        "node": node,
                        "t0_ns": tracer.t0_ns,
                        "offsets_ns": {str(p): 0 for p in node_set if p != node},
                    },
                },
            ]
        }
    for event in tracer.events:
        tid = event.get("tid")
        if tid in node_set:
            out[tid]["traceEvents"].append(dict(event))
    return out


def _clock_sync_of(trace):
    for event in trace.get("traceEvents", ()):
        if event.get("ph") == "M" and event.get("name") == CLOCK_SYNC:
            return event.get("args") or {}
    return {}


def aligned_events(traces):
    """Align per-node traces onto the reference node's absolute clock.

    Returns ``(shifted, plans)``: ``shifted`` is a list of
    ``(abs_us, node, event)`` where ``abs_us`` is the event's absolute
    time on the reference node's CLOCK_MONOTONIC in microseconds (NOT
    rebased to zero — critpath joins these against loadgen's raw
    ``monotonic_ns`` stamps, which live on the same clock when loadgen
    runs on the reference host); ``plans`` is the sorted
    ``(node, clock_sync, trace)`` list.  The reference clock is the
    lowest node id's; its ``offsets_ns`` map shifts every peer lane.
    Metadata (``ph: "M"``) events are excluded.
    """
    traces = list(traces)
    plans = []
    for i, trace in enumerate(traces):
        sync = _clock_sync_of(trace)
        node = sync.get("node", i)
        plans.append((node, sync, trace))
    plans.sort(key=lambda p: p[0])
    if not plans:
        return [], []

    ref_node, ref_sync, _ = plans[0]
    ref_offsets = ref_sync.get("offsets_ns") or {}

    shifted = []  # (abs_us, node, event)
    for node, sync, trace in plans:
        t0_ns = sync.get("t0_ns", 0)
        offset_ns = 0 if node == ref_node else int(ref_offsets.get(str(node), 0))
        for event in trace.get("traceEvents", ()):
            if event.get("ph") == "M":
                continue
            ev = dict(event)
            abs_us = (t0_ns + offset_ns) / 1000.0 + float(ev.get("ts", 0.0))
            shifted.append((abs_us, node, ev))
    return shifted, plans


def merge_traces(traces):
    """Merge per-node trace objects into one Chrome trace object.

    ``traces`` is an iterable of parsed Chrome trace dicts, each ideally
    carrying ``clock_sync`` metadata.  Traces without it get node ids
    assigned by position and no clock shift (documented degradation).
    """
    shifted, plans = aligned_events(traces)
    if not plans:
        return {"traceEvents": []}

    merged = []
    if shifted:
        base_us = min(abs_us for abs_us, _, _ in shifted)
    else:
        base_us = 0.0
    for abs_us, node, ev in shifted:
        ev["ts"] = abs_us - base_us
        ev["pid"] = node
        merged.append(ev)
    merged.sort(key=lambda e: e["ts"])

    _normalize_flows(merged)
    merged.extend(_flow_anchors(merged))

    meta = []
    for node, sync, trace in plans:
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": node,
                "tid": 0,
                "args": {"name": f"node {node}"},
            }
        )
        for event in trace.get("traceEvents", ()):
            if event.get("ph") == "M" and event.get("name") == "thread_name":
                ev = dict(event)
                ev["pid"] = node
                meta.append(ev)
    return {"traceEvents": meta + merged}


def _normalize_flows(events):
    """Rewrite flow phases in-place so each id has exactly one s and one
    f (earliest/latest), steps in between; single-record ids are
    removed."""
    by_id = {}
    for event in events:
        if event.get("cat") == "flow" and event.get("ph") in _FLOW_PHS:
            by_id.setdefault(event["id"], []).append(event)
    drop = []
    for records in by_id.values():
        if len(records) < 2:
            drop.extend(records)
            continue
        records.sort(key=lambda e: e["ts"])
        for record in records:
            record["ph"] = "t"
            record.pop("bp", None)
        records[0]["ph"] = "s"
        records[-1]["ph"] = "f"
        records[-1]["bp"] = "e"
    for record in drop:
        events.remove(record)


def _flow_anchors(events):
    """1 µs ph:"X" slices under each flow record: Perfetto binds flow
    arrows to slices, and milestone instants are not slices."""
    anchors = []
    for event in events:
        if event.get("cat") == "flow" and event.get("ph") in _FLOW_PHS:
            anchors.append(
                {
                    "name": event["name"],
                    "cat": "flow_anchor",
                    "ph": "X",
                    "pid": event["pid"],
                    "tid": event["tid"],
                    "ts": event["ts"],
                    "dur": 1.0,
                }
            )
    return anchors


def merge_files(paths, out_path=None):
    """Load per-node trace JSON files, merge, optionally write.

    Returns the merged trace object.
    """
    traces = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            traces.append(json.load(f))
    merged = merge_traces(traces)
    if out_path is not None:
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(merged, f)
    return merged
