"""``python -m mirbft_tpu.obsv`` — instrumented testengine ladder.

Runs a seeded Recorder with the observability plane enabled, prints the
per-phase consensus latency table (p50/p95/p99), and optionally writes a
Chrome trace-event file (``--trace``, open in ui.perfetto.dev), the
Prometheus exposition (``--prom``), or the registry JSON (``--json``).
"""

from __future__ import annotations

import argparse
import sys

from . import hooks
from .timeline import TimelineProfiler


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mirbft_tpu.obsv",
        description="Run an instrumented testengine ladder and report "
        "per-phase consensus latency.",
    )
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--clients", type=int, default=4)
    # Long enough that the run must pass stable checkpoints to keep
    # committing (>2 checkpoint windows), so the checkpoint phase has
    # samples in the table.
    parser.add_argument("--reqs", type=int, default=60,
                        help="requests per client")
    parser.add_argument("--batch-size", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--trace", metavar="PATH",
                        help="write a Chrome trace-event JSON file")
    parser.add_argument("--prom", action="store_true",
                        help="print Prometheus text exposition")
    parser.add_argument("--json", action="store_true",
                        help="print the registry snapshot as JSON")
    args = parser.parse_args(argv)

    # Import after argparse so --help stays instant.
    from ..testengine.engine import BasicRecorder

    registry, tracer = hooks.enable(trace=True)
    try:
        rec = BasicRecorder(
            args.nodes,
            args.clients,
            args.reqs,
            seed=args.seed,
            batch_size=args.batch_size,
            record=False,
        )
        for node in range(args.nodes):
            tracer.name_thread(node, f"node {node}")
        events = rec.drain_clients(max_steps=2_000_000)
        registry.gauge("mirbft_engine_sim_ms").set(rec.now)
        registry.counter("mirbft_engine_events_total").inc(events)

        profiler = TimelineProfiler.from_tracer(tracer)
        print(
            f"run: nodes={args.nodes} clients={args.clients} "
            f"reqs={args.reqs} batch_size={args.batch_size} "
            f"seed={args.seed} -> {events} events, sim {rec.now} ms"
        )
        print()
        print("consensus phase latency (simulated ms):")
        print(profiler.table())

        if args.trace:
            tracer.write(args.trace)
            print(f"\ntrace written to {args.trace} "
                  "(open in ui.perfetto.dev)")
        if args.prom:
            print()
            print(registry.prometheus_text(), end="")
        if args.json:
            print()
            print(registry.to_json(indent=2))
    finally:
        hooks.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
