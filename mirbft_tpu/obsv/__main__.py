"""``python -m mirbft_tpu.obsv`` — instrumented ladder, merge, and diff.

Default mode runs a seeded Recorder with the observability plane
enabled, prints the per-phase consensus latency table (p50/p95/p99), and
optionally writes a Chrome trace-event file (``--trace``, open in
ui.perfetto.dev), N per-node trace files plus their merge
(``--trace-dir``), the Prometheus exposition (``--prom``), or the
registry JSON (``--json``).

Tool modes (mutually exclusive with the run):

- ``--merge OUT IN [IN ...]`` — merge per-node traces into one
  Perfetto-loadable file with per-node process lanes (obsv/merge.py).
- ``--diff A B [--threshold PCT]`` — compare two trace/bench artifacts;
  prints a human summary plus one machine-readable JSON line, exits
  nonzero on a >= threshold regression, a ``growing`` resource-leak
  verdict in B, a device retrace-budget breach in B, or any recorded
  scalar/vector divergence in B (obsv/diff.py).  Either path may be a
  ``BENCH_stream.jsonl`` journal — torn or killed runs are recovered
  from their stage lines automatically.
- ``--postmortem DIR [--out PATH]`` — merge every node's newest flight
  recorder dump under DIR into one clock-aligned causal timeline ending
  at the failure (obsv/recorder.py); ``--out`` also writes the merged
  Chrome trace for Perfetto.
- ``--critpath DIR`` — build the per-request critical-path ledger from
  a run directory (per-node ``trace*.json`` files, optional
  ``records.json`` loadgen records) and print the per-percentile-band
  saturation attribution: which phase dominated, on which node
  (obsv/critpath.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import hooks
from .timeline import TimelineProfiler


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mirbft_tpu.obsv",
        description="Run an instrumented testengine ladder and report "
        "per-phase consensus latency; or merge/diff trace artifacts.",
    )
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--clients", type=int, default=4)
    # Long enough that the run must pass stable checkpoints to keep
    # committing (>2 checkpoint windows), so the checkpoint phase has
    # samples in the table.
    parser.add_argument("--reqs", type=int, default=60,
                        help="requests per client")
    parser.add_argument("--batch-size", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--sample-rate", type=float, default=None,
                        help="deterministic span sampling rate in (0,1]; "
                        "milestones/flows always kept")
    parser.add_argument("--trace", metavar="PATH",
                        help="write a Chrome trace-event JSON file")
    parser.add_argument("--trace-dir", metavar="DIR",
                        help="write per-node trace files plus merged.json")
    parser.add_argument("--prom", action="store_true",
                        help="print Prometheus text exposition")
    parser.add_argument("--json", action="store_true",
                        help="print the registry snapshot as JSON")
    parser.add_argument("--merge", nargs="+", metavar="PATH",
                        help="merge mode: OUT IN [IN ...]")
    parser.add_argument("--diff", nargs=2, metavar=("A", "B"),
                        help="diff mode: compare two trace/bench artifacts")
    parser.add_argument("--threshold", type=float, default=None,
                        help="regression threshold percent for --diff")
    parser.add_argument("--postmortem", metavar="DIR",
                        help="postmortem mode: merge flight recorder "
                        "dumps under DIR into one causal timeline")
    parser.add_argument("--critpath", metavar="DIR",
                        help="critical-path mode: per-request phase "
                        "attribution from a run directory of per-node "
                        "trace*.json files (+ optional records.json)")
    parser.add_argument("--out", metavar="PATH",
                        help="write the merged postmortem trace here "
                        "(--postmortem only)")
    parser.add_argument("--limit", type=int, default=200,
                        help="timeline lines to print (--postmortem, "
                        "default 200)")
    args = parser.parse_args(argv)

    if args.postmortem:
        return _postmortem_main(args)
    if args.critpath:
        return _critpath_main(args)
    if args.diff:
        return _diff_main(args)
    if args.merge:
        return _merge_main(args)
    return _run_main(args)


def _critpath_main(args) -> int:
    from .critpath import attribute, attribution_table, ledger_from_dir

    try:
        ledger, n_traces = ledger_from_dir(args.critpath)
    except (FileNotFoundError, NotADirectoryError) as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if not n_traces:
        print(f"no trace*.json files under {args.critpath}", file=sys.stderr)
        return 2
    attribution = attribute(ledger)
    print(
        f"critpath: {len(ledger)} committed flow(s) from {n_traces} "
        f"node trace(s) under {args.critpath}"
    )
    print()
    print(attribution_table(attribution))
    print(json.dumps({"bands": attribution}))
    return 0


def _diff_main(args) -> int:
    from .diff import DEFAULT_THRESHOLD_PCT, diff_files, render_report

    threshold = (
        args.threshold if args.threshold is not None else DEFAULT_THRESHOLD_PCT
    )
    report = diff_files(args.diff[0], args.diff[1], threshold_pct=threshold)
    print(render_report(report))
    print(json.dumps(report))
    return 0 if report["ok"] else 1


def _postmortem_main(args) -> int:
    from .recorder import postmortem

    try:
        result = postmortem(args.postmortem, out_path=args.out,
                            limit=args.limit)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    dumps = result["dumps"]
    print(f"postmortem: {len(dumps)} node dump(s) under {args.postmortem}")
    for node, path in sorted(dumps.items()):
        print(f"  node {node}: {path}")
    print()
    print("causal timeline (clock-aligned, oldest first, ends at failure):")
    print(result["timeline"] or "  (no entries)")
    if args.out:
        events = len(result["merged"].get("traceEvents", ()))
        print(f"\nmerged trace ({events} events) written to {args.out} "
              "(open in ui.perfetto.dev)")
    return 0


def _merge_main(args) -> int:
    from .merge import merge_files

    if len(args.merge) < 3:
        print("--merge needs OUT and at least two inputs", file=sys.stderr)
        return 2
    out, inputs = args.merge[0], args.merge[1:]
    merged = merge_files(inputs, out_path=out)
    print(
        f"merged {len(inputs)} traces "
        f"({len(merged['traceEvents'])} events) into {out}"
    )
    return 0


def _run_main(args) -> int:
    # Import after argparse so --help stays instant.
    from ..testengine.engine import BasicRecorder
    from .merge import merge_traces, split_node_traces

    registry, tracer = hooks.enable(
        trace=True, sample_rate=args.sample_rate, sample_seed=args.seed
    )
    try:
        rec = BasicRecorder(
            args.nodes,
            args.clients,
            args.reqs,
            seed=args.seed,
            batch_size=args.batch_size,
            record=False,
        )
        for node in range(args.nodes):
            tracer.name_thread(node, f"node {node}")
        events = rec.drain_clients(max_steps=2_000_000)
        registry.gauge("mirbft_engine_sim_ms").set(rec.now)
        registry.counter("mirbft_engine_events_total").inc(events)

        profiler = TimelineProfiler.from_tracer(tracer)
        print(
            f"run: nodes={args.nodes} clients={args.clients} "
            f"reqs={args.reqs} batch_size={args.batch_size} "
            f"seed={args.seed} -> {events} events, sim {rec.now} ms"
        )
        print()
        print("consensus phase latency (simulated ms):")
        print(profiler.table())

        if args.trace:
            tracer.write(args.trace)
            print(f"\ntrace written to {args.trace} "
                  "(open in ui.perfetto.dev)")
        if args.trace_dir:
            os.makedirs(args.trace_dir, exist_ok=True)
            per_node = split_node_traces(tracer, range(args.nodes))
            paths = []
            for node, trace in per_node.items():
                path = os.path.join(args.trace_dir, f"node{node}.trace.json")
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(trace, f)
                paths.append(path)
            merged_path = os.path.join(args.trace_dir, "merged.trace.json")
            with open(merged_path, "w", encoding="utf-8") as f:
                json.dump(merge_traces(per_node.values()), f)
            print(f"\nper-node traces: {', '.join(paths)}")
            print(f"merged trace:    {merged_path} (open in ui.perfetto.dev)")
        if args.prom:
            print()
            print(registry.prometheus_text(), end="")
        if args.json:
            print()
            print(registry.to_json(indent=2))
    finally:
        hooks.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
