"""The bounded-queue telemetry shim: every bounded hot-path queue's
single sanctioned emission point for the ``mirbft_queue_*`` series.

Saturation attribution (obsv/critpath.py) names the *phase* where a
request's latency went; these series name the *queue* that absorbed the
wait, so the two lines of evidence corroborate each other.  Three
uniform families, labeled ``queue="<name>"``:

- ``mirbft_queue_depth`` — items queued right after a put/get (gauge).
- ``mirbft_queue_wait_seconds`` — enqueue→dequeue residency per item
  (histogram).
- ``mirbft_queue_saturated_total`` — put attempts that found the queue
  at capacity: blocked (processor stages, app apply), dropped-oldest
  (transport peer lanes), or forced a flush (device staging).

Two entry points:

- :class:`BoundedQueue` — a drop-in for ``queue.Queue`` used by queues
  with stdlib semantics (processor stage hand-offs, the CommitStream
  apply queue).  Items are stamped at enqueue so the wait histogram is
  true per-item residency.
- :class:`QueueTelemetry` — a bare handle for queues whose data
  structure cannot be swapped (the transport's latency-emulating deque,
  the device plane's staged-row buffer); the owner calls ``depth()`` /
  ``wait()`` / ``saturated()`` at its own put/drain points.

Every record is behind ``hooks.enabled`` (one branch when off — the
<2% disabled-overhead contract) and every registration catches
``CardinalityError``: a queue past the documented budget loses its
series, never its queue.  Lint rule W19 confines ``mirbft_queue_*``
emission to this module so an ad-hoc queue cannot bypass telemetry.
"""

from __future__ import annotations

import queue as _queue_mod
import time

from . import hooks
from .metrics import CardinalityError

_DEPTH = "mirbft_queue_depth"
_WAIT = "mirbft_queue_wait_seconds"
_SATURATED = "mirbft_queue_saturated_total"


class QueueTelemetry:
    """Emission handle for one named bounded queue.

    Handles are looked up lazily against whatever registry ``hooks``
    currently carries and re-resolved when ``enable()`` installs a new
    one, so a long-lived queue survives enable/disable cycles.  All
    three record methods are no-ops (one branch) when observability is
    off.
    """

    __slots__ = ("name", "_registry", "_depth", "_wait", "_saturated")

    def __init__(self, name: str):
        self.name = name
        self._registry = None
        self._depth = None
        self._wait = None
        self._saturated = None

    def _handles(self):
        registry = hooks.metrics
        if registry is None:
            return None
        if registry is not self._registry:
            try:
                self._depth = registry.gauge(_DEPTH, queue=self.name)
                self._wait = registry.histogram(_WAIT, queue=self.name)
                self._saturated = registry.counter(
                    _SATURATED, queue=self.name
                )
            except CardinalityError:
                # Over the documented budget: this queue loses its
                # series (depth/wait/saturated all-or-nothing), the
                # queue itself keeps working.
                self._depth = self._wait = self._saturated = None
            self._registry = registry
        return self._depth

    def depth(self, n: int) -> None:
        if hooks.enabled and self._handles() is not None:
            self._depth.set(n)

    def wait(self, seconds: float) -> None:
        if hooks.enabled and self._handles() is not None:
            self._wait.observe(seconds)

    def saturated(self, n: int = 1) -> None:
        if hooks.enabled and self._handles() is not None:
            self._saturated.inc(n)


class BoundedQueue:
    """``queue.Queue`` semantics plus uniform backpressure telemetry.

    Items are stored as ``(enqueue_perf_counter, item)`` so the wait
    histogram observes true enqueue→dequeue residency; the stamp is 0.0
    when observability was off at enqueue time (such items skip the
    histogram — a residency measured across an enable() edge would be
    garbage).  ``put``/``get`` raise ``queue.Full``/``queue.Empty``
    exactly like the stdlib class.
    """

    __slots__ = ("name", "maxsize", "_q", "telemetry")

    def __init__(self, name: str, maxsize: int = 0):
        self.name = name
        self.maxsize = maxsize
        self._q = _queue_mod.Queue(maxsize=maxsize)
        self.telemetry = QueueTelemetry(name)

    def put(self, item, block: bool = True, timeout: float | None = None):
        stamp = time.perf_counter() if hooks.enabled else 0.0
        entry = (stamp, item)
        try:
            self._q.put_nowait(entry)
        except _queue_mod.Full:
            # The backpressure edge: count the saturated attempt, then
            # fall through to the caller's blocking discipline.
            self.telemetry.saturated()
            self._q.put(entry, block=block, timeout=timeout)
        self.telemetry.depth(self._q.qsize())

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: float | None = None):
        stamp, item = self._q.get(block=block, timeout=timeout)
        if hooks.enabled:
            if stamp:
                self.telemetry.wait(time.perf_counter() - stamp)
            self.telemetry.depth(self._q.qsize())
        return item

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()
