"""Process-global observability switchboard.

Instrumented modules import this module (never the registry or tracer
directly) and guard every hot-path record behind ``if hooks.enabled:`` —
one module-attribute load and a branch when observability is off, which
keeps the disabled overhead unmeasurable (<2% on the testengine ladder,
asserted by the acceptance bench).

``enable()`` installs a live :class:`~mirbft_tpu.obsv.metrics.Registry`
(and optionally a :class:`~mirbft_tpu.obsv.trace.Tracer`); ``disable()``
restores the no-op state.  ``sim_now`` is the testengine's simulated
clock in ms — the Recorder publishes it as it advances, so milestone
instants carry simulated time alongside the monotonic wall timestamp.

``sample_rate`` (set via ``enable(sample_rate=...)``) thins ph:"X" spans
deterministically for long-running ladders; milestone instants and flow
records are never sampled out, so the timeline profiler and merge.py
always see the full consensus skeleton.

Everything here is clock-free except through the tracer/registry, which
use ``time.perf_counter``-family monotonic sources only (enforced by the
W7 lint rule).
"""

from __future__ import annotations

import time

from .metrics import CardinalityError

enabled = False
metrics = None  # Registry when enabled, else None
tracer = None  # Tracer when tracing was requested, else None
recorder = None  # FlightRecorder when wired, else None
sim_now = None  # simulated ms (testengine runs), None under the runtime
sample_rate = None  # span sampling rate in (0, 1], None = keep everything
shadow = None  # ShadowSampler when the divergence oracle is wired, else None

# (node, epoch) -> perf_counter at "epoch.changing"; consumed by
# "epoch.active" to observe mirbft_epoch_change_seconds.  Cleared on
# enable/disable so back-to-back runs do not cross-pollinate durations.
_epoch_change_started: dict = {}


def enable(
    registry=None,
    trace=False,
    sample_rate=None,
    sample_seed=0,
    recorder=None,
    shadow=None,
):
    """Turn observability on.  Returns ``(metrics, tracer)``.

    ``registry`` defaults to a fresh Registry; ``trace=True`` also
    installs a fresh Tracer (span/instant capture is more expensive than
    counters, so it is opt-in even when metrics are on).
    ``sample_rate`` keeps roughly that fraction of ph:"X" spans via a
    deterministic seed-derived stride (see trace.SpanSampler); it never
    touches milestones or flow events.  ``recorder`` optionally wires a
    :class:`~mirbft_tpu.obsv.recorder.FlightRecorder` so milestones and
    StateEvents also land in the black-box ring (see obsv/recorder.py).
    ``shadow`` optionally wires a
    :class:`~mirbft_tpu.obsv.shadow.ShadowSampler` — the scalar/vector
    divergence oracle the client tracker's ack frames feed.
    """
    global enabled, metrics, tracer, sim_now
    from .metrics import Registry
    from .trace import SpanSampler, Tracer

    metrics = registry if registry is not None else Registry()
    sampler = None
    if sample_rate is not None and sample_rate < 1.0:
        sampler = SpanSampler(sample_rate, seed=sample_seed)
    tracer = Tracer(sampler=sampler) if trace else None
    sim_now = None
    globals()["sample_rate"] = sample_rate
    globals()["recorder"] = recorder
    globals()["shadow"] = shadow
    _epoch_change_started.clear()
    enabled = True
    return metrics, tracer


def disable():
    """Restore the no-op state (instrumentation sites become one branch)."""
    global enabled, metrics, tracer, recorder, sim_now, sample_rate, shadow
    enabled = False
    metrics = None
    tracer = None
    recorder = None
    sim_now = None
    sample_rate = None
    shadow = None
    _epoch_change_started.clear()


def milestone(name, node, seq, epoch=None, bucket=None):
    """Emit a protocol milestone: instant event + flow record + counter.

    Call sites still guard with ``if hooks.enabled:`` so the disabled
    cost stays a single branch; this function only re-checks the tracer
    and registry.

    ``epoch``/``bucket`` mint the flow id ``"<epoch>.<seq>.<bucket>"``
    when this is the first milestone for ``(node, seq)``; terminal sites
    (``seq.committed``) may omit them — the tracer resolves the id from
    its open-flow table.  Checkpoint milestones (``ckpt.*``) get their
    own flow family ``"c.<seq>"`` of step records that merge.py promotes
    to s/f across node lanes.
    """
    args = {"node": node, "seq": seq, "sim_ms": sim_now}
    if epoch is not None:
        args["epoch"] = epoch
    if bucket is not None:
        args["bucket"] = bucket
    t = tracer
    if t is not None:
        t.instant(name, cat="consensus", tid=node, args=args)
        if name.startswith("ckpt."):
            t.flow_step(name, tid=node, flow_id=f"c.{seq}")
        else:
            t.flow_milestone(name, tid=node, seq_no=seq, epoch=epoch, bucket=bucket)
    r = recorder
    if r is not None:
        r.record_milestone(name, node=node, args=args)
    m = metrics
    if m is not None:
        try:
            if epoch is not None and bucket is not None:
                m.counter(
                    "mirbft_seq_milestones_total",
                    milestone=name,
                    epoch=str(epoch),
                    bucket=str(bucket),
                ).inc()
            else:
                m.counter("mirbft_seq_milestones_total", milestone=name).inc()
        except CardinalityError:
            pass  # over budget: keep the instant, drop the counter


def epoch_milestone(name, node, epoch):
    """Emit an epoch-change milestone: ``epoch.changing`` when a node
    constructs and broadcasts its epoch-change message, ``epoch.active``
    when the new epoch's ActiveEpoch takes over.

    Each milestone is an instant + a flow step on the per-epoch flow
    family ``"e.<epoch>"`` (so merge.py can stitch the change across node
    lanes, like checkpoints' ``"c.<seq>"``) + a counter.  The changing ->
    active pair additionally times the outage:
    ``mirbft_epoch_change_seconds`` observes how long this node spent
    between giving up on the old epoch and activating the new one — the
    liveness gap chaos runs assert on.  Epoch 0 activates at boot with no
    preceding "changing", so it never records a duration.
    """
    args = {"node": node, "epoch": epoch, "sim_ms": sim_now}
    t = tracer
    if t is not None:
        t.instant(name, cat="consensus", tid=node, args=args)
        t.flow_step(name, tid=node, flow_id=f"e.{epoch}")
    r = recorder
    if r is not None:
        r.record_milestone(name, node=node, args=args)
    m = metrics
    if m is not None:
        try:
            m.counter(
                "mirbft_epoch_events_total",
                event=name.split(".", 1)[1],
                epoch=str(epoch),
            ).inc()
        except CardinalityError:
            pass  # over budget: keep the instant, drop the counter
        if name == "epoch.changing":
            _epoch_change_started[(node, epoch)] = time.perf_counter()
        elif name == "epoch.active":
            start = _epoch_change_started.pop((node, epoch), None)
            if start is not None:
                m.histogram("mirbft_epoch_change_seconds").observe(
                    time.perf_counter() - start
                )


def record_ack_batch(plane, n):
    """Record one ack frame/batch absorbed by an ack plane: event count
    plus batch-size distribution, labeled ``plane="host"`` (the
    _FastAcks/scalar paths in step_ack_many) or ``plane="device"`` (one
    device_tracker kernel flush).  The bench ackplane rung derives its
    events/s keys from these counters."""
    m = metrics
    if m is None:
        return
    from .metrics import ACK_BATCH_BUCKETS

    m.counter("mirbft_ack_events_total", plane=plane).inc(n)
    m.histogram(
        "mirbft_ack_batch_size", ACK_BATCH_BUCKETS, plane=plane
    ).observe(n)


def record_flush(plane, path, items, seconds=None):
    """Record one crypto-plane flush/launch/readback: how many digests or
    verdicts moved through which path (device, host, readback, rescued,
    inline), and how long the blocking part took.  ``seconds=None`` means
    the call had no blocking component worth timing (e.g. inline bypass).
    """
    m = metrics
    if m is None:
        return
    m.counter("mirbft_crypto_flush_total", plane=plane, path=path).inc()
    m.counter("mirbft_crypto_items_total", plane=plane, path=path).inc(items)
    if plane == "signature":
        from .metrics import ACK_BATCH_BUCKETS

        # Burst-size distribution of the batched verify stage: how well
        # speculative admission is coalescing signature checks (a pile-up
        # at bucket 1 means the pipeline degenerated to per-item verify).
        m.histogram(
            "mirbft_crypto_verify_batch_size", ACK_BATCH_BUCKETS, path=path
        ).observe(items)
    if seconds is not None:
        m.histogram("mirbft_crypto_flush_seconds", plane=plane).observe(seconds)
    t = tracer
    if t is not None and seconds is not None:
        t.complete(
            "crypto." + plane + "." + path,
            cat="crypto",
            tid=-1,
            dur_s=seconds,
            args={"items": items, "sim_ms": sim_now},
        )
