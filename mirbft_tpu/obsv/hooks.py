"""Process-global observability switchboard.

Instrumented modules import this module (never the registry or tracer
directly) and guard every hot-path record behind ``if hooks.enabled:`` —
one module-attribute load and a branch when observability is off, which
keeps the disabled overhead unmeasurable (<2% on the testengine ladder,
asserted by the acceptance bench).

``enable()`` installs a live :class:`~mirbft_tpu.obsv.metrics.Registry`
(and optionally a :class:`~mirbft_tpu.obsv.trace.Tracer`); ``disable()``
restores the no-op state.  ``sim_now`` is the testengine's simulated
clock in ms — the Recorder publishes it as it advances, so milestone
instants carry simulated time alongside the monotonic wall timestamp.

Everything here is clock-free except through the tracer/registry, which
use ``time.perf_counter``-family monotonic sources only (enforced by the
W7 lint rule).
"""

from __future__ import annotations

enabled = False
metrics = None  # Registry when enabled, else None
tracer = None  # Tracer when tracing was requested, else None
sim_now = None  # simulated ms (testengine runs), None under the runtime


def enable(registry=None, trace=False):
    """Turn observability on.  Returns ``(metrics, tracer)``.

    ``registry`` defaults to a fresh Registry; ``trace=True`` also
    installs a fresh Tracer (span/instant capture is more expensive than
    counters, so it is opt-in even when metrics are on).
    """
    global enabled, metrics, tracer, sim_now
    from .metrics import Registry
    from .trace import Tracer

    metrics = registry if registry is not None else Registry()
    tracer = Tracer() if trace else None
    sim_now = None
    enabled = True
    return metrics, tracer


def disable():
    """Restore the no-op state (instrumentation sites become one branch)."""
    global enabled, metrics, tracer, sim_now
    enabled = False
    metrics = None
    tracer = None
    sim_now = None


def milestone(name, node, seq):
    """Emit a protocol-milestone instant event (no-op without a tracer).

    Call sites still guard with ``if hooks.enabled:`` so the disabled
    cost stays a single branch; this function only re-checks the tracer.
    """
    t = tracer
    if t is not None:
        t.instant(
            name,
            cat="consensus",
            tid=node,
            args={"node": node, "seq": seq, "sim_ms": sim_now},
        )


def record_flush(plane, path, items, seconds=None):
    """Record one crypto-plane flush/launch/readback: how many digests or
    verdicts moved through which path (device, host, readback, rescued,
    inline), and how long the blocking part took.  ``seconds=None`` means
    the call had no blocking component worth timing (e.g. inline bypass).
    """
    m = metrics
    if m is None:
        return
    m.counter("mirbft_crypto_flush_total", plane=plane, path=path).inc()
    m.counter("mirbft_crypto_items_total", plane=plane, path=path).inc(items)
    if seconds is not None:
        m.histogram("mirbft_crypto_flush_seconds", plane=plane).observe(seconds)
    t = tracer
    if t is not None and seconds is not None:
        t.complete(
            "crypto." + plane + "." + path,
            cat="crypto",
            tid=-1,
            dur_s=seconds,
            args={"items": items, "sim_ms": sim_now},
        )
