"""Device-plane telemetry: kernel timings, retrace detection, transfers.

The host-protocol obsv stack (metrics/trace/recorder) is blind to the
device plane — the ``ops/`` kernels, ``parallel/sharding.py`` and the
testengine crypto planes run jit-compiled programs whose compile storms,
silent retraces and transfer volumes never reach the catalog.  This
module closes that gap with a single decorator:

    from ..obsv import device as _device

    @_device.instrument("sha256_digest")
    def sha256_digest_words(blocks, n_blocks): ...

Per call (only while capture is active — see below) the wrapper

- observes wall time in ``mirbft_device_kernel_seconds{kernel}``;
- computes an *abstract-shape signature* of the arguments (shape+dtype
  for arrays, bucketed length for sequences, value for scalars — the
  same abstraction jit uses to decide whether to retrace) and bumps
  ``mirbft_device_retraces_total{fn}`` whenever a new signature shows
  up.  A per-function retrace budget turns unbounded-shape
  recompilation — the classic silent TPU perf killer — into a gate
  failure (``report()["retrace_breaches"]``, enforced by ``obsv
  --diff``);
- estimates host->device / device->host traffic from argument/result
  nbytes into ``mirbft_device_transfer_bytes_total{direction}``.

``sync=True`` (default) blocks on the result inside the timed window so
the histogram sees real device time; entry points whose callers measure
async dispatch themselves (the chain-checksum microbenches) pass
``sync=False`` so instrumentation never perturbs their protocol.

Gating: the wrapper is active when either ``start_capture(registry)``
installed a capture registry (bench runs) or ``hooks.enabled`` is on
(tests, chaos).  Off, the cost is one module-attribute load and a
branch — same <2% discipline as every other obsv hook.

``memory_sample()`` reports live-buffer and HBM gauges; the
ResourceSampler calls it on its existing cadence, and it never imports
jax itself (``sys.modules`` guard) so pure-host runs stay jax-free.
"""

from __future__ import annotations

import functools
import sys
import time

from .metrics import CardinalityError

#: New distinct abstract signatures tolerated per function before the
#: function lands in ``report()["retrace_breaches"]``.  Steady-state
#: callers go through ops.batching's power-of-two buckets, so a handful
#: of signatures is normal; growth past the budget means some caller is
#: feeding unbucketed shapes and recompiling per call.
DEFAULT_RETRACE_BUDGET = 8

_capture_registry = None  # Registry while start_capture() is active
_retrace_budget = DEFAULT_RETRACE_BUDGET
_signatures: dict = {}  # fn name -> set of abstract signatures seen
_retraces: dict = {}  # fn name -> count of new-signature events
_breaches: list = []  # fn names that exceeded the budget (insertion order)


def reset():
    """Forget all signatures, counts and breaches (new bench run)."""
    _signatures.clear()
    _retraces.clear()
    del _breaches[:]


def start_capture(registry, retrace_budget=None):
    """Route device telemetry into ``registry`` independently of the
    hooks switchboard (bench stages toggle hooks themselves; the device
    capture must span the whole run)."""
    global _capture_registry, _retrace_budget
    _capture_registry = registry
    if retrace_budget is not None:
        _retrace_budget = retrace_budget


def stop_capture():
    global _capture_registry, _retrace_budget
    _capture_registry = None
    _retrace_budget = DEFAULT_RETRACE_BUDGET


def _registry():
    if _capture_registry is not None:
        return _capture_registry
    from . import hooks

    if hooks.enabled:
        return hooks.metrics
    return None


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _abstract(value):
    """Abstract signature of one argument — the granularity at which
    jit retraces.  Sequences are bucketed to the next power of two so a
    list-taking entry point (verify_batch, aggregate_signatures) is not
    charged a retrace per distinct length (ops.batching pads to pow2
    buckets before tracing)."""
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return ("static", value)
    if isinstance(value, (list, tuple)):
        return ("seq", type(value).__name__, _next_pow2(len(value)))
    return ("obj", type(value).__name__)


def _signature(args, kwargs):
    sig = tuple(_abstract(a) for a in args)
    if kwargs:
        sig += tuple((k, _abstract(v)) for k, v in sorted(kwargs.items()))
    return sig


def _nbytes(value) -> int:
    n = getattr(value, "nbytes", None)
    if isinstance(n, int):
        return n
    if isinstance(value, (list, tuple)):
        return sum(_nbytes(v) for v in value)
    return 0


def _note_signature(fn_name, sig, registry):
    seen = _signatures.get(fn_name)
    if seen is None:
        seen = _signatures[fn_name] = set()
    if sig in seen:
        return
    seen.add(sig)
    _retraces[fn_name] = _retraces.get(fn_name, 0) + 1
    if _retraces[fn_name] > _retrace_budget and fn_name not in _breaches:
        _breaches.append(fn_name)
    try:
        registry.counter("mirbft_device_retraces_total", fn=fn_name).inc()
    except CardinalityError:
        pass  # over budget: the dict above still has the truth


def instrument(kernel, *, sync=True, fn_name=None):
    """Decorator wrapping one device-plane entry point.

    ``kernel`` labels the timing histogram; ``fn_name`` labels the
    retrace counter (defaults to the wrapped function's ``__name__`` —
    pass it explicitly for closures that all compile as ``run``).
    ``sync=False`` skips the block-until-ready so entry points with
    their own async measurement protocol stay undisturbed.
    """

    def deco(fn):
        label = fn_name or getattr(fn, "__name__", kernel)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            registry = _registry()
            if registry is None:
                return fn(*args, **kwargs)
            _note_signature(label, _signature(args, kwargs), registry)
            start = time.perf_counter()
            out = fn(*args, **kwargs)
            if sync:
                try:
                    import jax

                    out = jax.block_until_ready(out)
                except Exception:
                    pass  # tracers / non-jax results: timing stays dispatch-only
            elapsed = time.perf_counter() - start
            try:
                registry.histogram(
                    "mirbft_device_kernel_seconds", kernel=kernel
                ).observe(elapsed)
                h2d = sum(_nbytes(a) for a in args)
                if h2d:
                    registry.counter(
                        "mirbft_device_transfer_bytes_total", direction="h2d"
                    ).inc(h2d)
                d2h = _nbytes(out)
                if d2h:
                    registry.counter(
                        "mirbft_device_transfer_bytes_total", direction="d2h"
                    ).inc(d2h)
            except CardinalityError:
                pass
            return out

        return wrapper

    return deco


def memory_sample():
    """Live-buffer and HBM usage, or None when jax was never imported.

    Returns ``{"live_buffers": int, "live_buffer_bytes": int,
    "hbm_bytes": int}``.  ``hbm_bytes`` is 0 on backends without
    ``memory_stats`` (CPU).  Never imports jax itself: if the process
    has not paid for jax, neither does its resource sampling.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        arrays = jax.live_arrays()
        live = len(arrays)
        live_bytes = 0
        for a in arrays:
            n = getattr(a, "nbytes", 0)
            if isinstance(n, int):
                live_bytes += n
        hbm = 0
        stats = getattr(jax.devices()[0], "memory_stats", None)
        if callable(stats):
            raw = stats()
            if raw:
                hbm = int(raw.get("bytes_in_use", 0))
        return {
            "live_buffers": live,
            "live_buffer_bytes": live_bytes,
            "hbm_bytes": hbm,
        }
    except Exception:
        return None


def report(registry):
    """Summarize the capture for the bench payload's ``device`` section.

    Pulls kernel timings from the registry snapshot and retrace truth
    from the module dicts (the dicts survive CardinalityError drops)."""
    snap = registry.snapshot()
    kernels = {}
    entry = snap.get("mirbft_device_kernel_seconds")
    if entry:
        for series in entry.get("series", ()):
            name = series["labels"].get("kernel", "?")
            count = series.get("count", 0)
            total = series.get("sum", 0.0)
            kernels[name] = {
                "count": count,
                "total_s": total,
                "mean_ms": (total / count * 1e3) if count else 0.0,
            }
    transfers = {}
    entry = snap.get("mirbft_device_transfer_bytes_total")
    if entry:
        for series in entry.get("series", ()):
            transfers[series["labels"].get("direction", "?")] = series["value"]
    divergence = 0
    entry = snap.get("mirbft_divergence_total")
    if entry:
        divergence = sum(s["value"] for s in entry.get("series", ()))
    return {
        "kernel_seconds": kernels,
        "retraces": dict(_retraces),
        "retrace_budget": _retrace_budget,
        "retrace_breaches": list(_breaches),
        "transfer_bytes": transfers,
        "divergence_total": divergence,
    }
