"""Read-only status snapshots and the ASCII dashboard.

Rebuild of the reference's status package (reference: status/status.go:
73-296): a JSON-able deep snapshot of every tracker, taken on demand via
the serializer, plus a pretty renderer showing buckets, sequences,
checkpoints, and client windows.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

from .core.epoch_target import TargetState
from .core.sequence import SeqState


@dataclass
class BucketStatus:
    id: int
    leader: bool
    sequences: list = field(default_factory=list)  # [str: sequence states]


@dataclass
class CheckpointStatus:
    seq_no: int
    max_agreements: int
    net_quorum: bool
    local_decision: bool
    stable: bool


@dataclass
class ClientStatus:
    client_id: int
    low_watermark: int
    high_watermark: int
    next_ready_mark: int
    # per req_no in window: "" (empty), A (acked), W (weak), S (strong),
    # R (ready/local), C (committed)
    allocated: list = field(default_factory=list)


@dataclass
class EpochChangeStatus:
    source: int
    msgs: list = field(default_factory=list)  # [(digest_hex, [ackers])]


@dataclass
class EpochTargetStatus:
    number: int
    state: str
    epoch_changes: list = field(default_factory=list)
    echos: list = field(default_factory=list)
    readies: list = field(default_factory=list)
    suspicions: list = field(default_factory=list)


@dataclass
class NetworkConfigStatus:
    """The active consensus configuration and the reconfiguration
    pipeline's position: which config this node runs under (epoch it is
    serving, the checkpoint it was re-anchored at), plus how many
    committed reconfigurations are pending adoption and how many have
    been adopted over this process's lifetime."""

    epoch: int
    first_seq: int  # checkpoint seq_no the active config anchors at
    nodes: list = field(default_factory=list)
    f: int = 0
    number_of_buckets: int = 0
    checkpoint_interval: int = 0
    max_epoch_length: int = 0
    pending_reconfigurations: int = 0
    reconfigs_adopted: int = 0
    retired: bool = False


@dataclass
class StateMachineStatus:
    node_id: int
    low_watermark: int
    high_watermark: int
    epoch_tracker: EpochTargetStatus | None
    client_windows: list = field(default_factory=list)
    buckets: list = field(default_factory=list)
    checkpoints: list = field(default_factory=list)
    # Skew signal: in-flight (allocated-but-uncommitted) sequences per
    # bucket, and max/median of that vector — 1.0 means balanced load,
    # large means one leader's bucket is absorbing the hot clients.
    bucket_backlog: list = field(default_factory=list)
    bucket_imbalance: float = 0.0
    network_config: NetworkConfigStatus | None = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, default=str)

    def pretty(self) -> str:
        return pretty(self)


_SEQ_CHARS = {
    SeqState.UNINITIALIZED: ".",
    SeqState.ALLOCATED: "a",
    SeqState.PENDING_REQUESTS: "q",
    SeqState.READY: "r",
    SeqState.PREPREPARED: "Q",
    SeqState.PREPARED: "P",
    SeqState.COMMITTED: "C",
}


def _imbalance_ratio(backlog: list) -> float:
    """max/median of the per-bucket backlog vector (median floored at 1
    so an idle cluster reads as ratio == max, not a division blowup).
    1.0 when perfectly balanced or empty."""
    if not backlog:
        return 0.0
    ordered = sorted(backlog)
    n = len(ordered)
    mid = ordered[n // 2] if n % 2 else (ordered[n // 2 - 1] + ordered[n // 2]) / 2.0
    return max(ordered) / max(float(mid), 1.0)


def _client_status(client) -> ClientStatus:
    allocated = []
    for crn in client.req_nos():
        if crn.committed is not None:
            allocated.append("C")
        elif any(d in crn.my_requests for d in crn.strong_requests):
            allocated.append("R")
        elif crn.strong_requests:
            allocated.append("S")
        elif crn.weak_requests:
            allocated.append("W")
        elif crn.requests:
            allocated.append("A")
        else:
            allocated.append("")
    while allocated and allocated[-1] == "":
        allocated.pop()
    return ClientStatus(
        client_id=client.client_state.id,
        low_watermark=client.low_watermark,
        high_watermark=client.high_watermark,
        next_ready_mark=client.next_ready_mark,
        allocated=allocated,
    )


def state_machine_status(machine) -> StateMachineStatus:
    """Snapshot a core.state_machine.StateMachine.  Must be called from the
    thread that owns the machine (the serializer does this)."""
    if machine.my_config is None or machine.epoch_tracker is None or \
            machine.epoch_tracker.current_epoch is None:
        return StateMachineStatus(
            node_id=machine.my_config.id if machine.my_config else -1,
            low_watermark=0,
            high_watermark=0,
            epoch_tracker=None,
        )

    target = machine.epoch_tracker.current_epoch

    epoch_changes = []
    for origin in sorted(target.changes):
        cert = target.changes[origin]
        msgs = [
            (digest.hex()[:16], sorted(parsed.acks))
            for digest, parsed in sorted(cert.parsed_by_digest.items())
        ]
        epoch_changes.append(EpochChangeStatus(source=origin, msgs=msgs))

    def voters(table):
        out = []
        for _cfg, votes in table.values():
            out.extend(votes)
        return sorted(set(out))

    tracker_status = EpochTargetStatus(
        number=target.number,
        state=TargetState(target.state).name,
        epoch_changes=epoch_changes,
        echos=voters(target.echos),
        readies=voters(target.readies),
        suspicions=sorted(target.suspicions),
    )

    low = high = 0
    buckets = []
    active = target.active_epoch
    if active is not None and active.sequences:
        low = active.low_watermark()
        high = active.high_watermark()
        per_bucket: dict[int, list] = {b: [] for b in active.buckets}
        for seq_no in range(low, high + 1):
            seq = active.sequence(seq_no)
            per_bucket[active.seq_bucket(seq_no)].append(
                _SEQ_CHARS[seq.state]
            )
        buckets = [
            BucketStatus(
                id=b,
                leader=active.buckets[b] == machine.my_config.id,
                sequences=per_bucket[b],
            )
            for b in sorted(per_bucket)
        ]
        backlog = [
            sum(1 for c in per_bucket[b] if c not in (".", "C"))
            for b in sorted(per_bucket)
        ]
        imbalance = _imbalance_ratio(backlog)
    else:
        backlog = []
        imbalance = 0.0

    checkpoints = [
        CheckpointStatus(
            seq_no=cp.seq_no,
            max_agreements=max(
                (len(nodes) for nodes in cp.votes.values()), default=0
            ),
            net_quorum=cp.committed_value is not None,
            local_decision=cp.my_value is not None,
            stable=cp.stable,
        )
        for cp in sorted(
            machine.checkpoint_tracker.checkpoint_map.values(),
            key=lambda c: c.seq_no,
        )
    ]

    clients = [
        _client_status(machine.client_tracker.clients[cs.id])
        for cs in machine.client_tracker.client_states
    ]

    config_status = None
    commit_state = machine.commit_state
    if commit_state is not None and commit_state.active_state is not None:
        active = commit_state.active_state
        config_status = NetworkConfigStatus(
            epoch=target.number,
            first_seq=commit_state.low_watermark,
            nodes=list(active.config.nodes),
            f=active.config.f,
            number_of_buckets=active.config.number_of_buckets,
            checkpoint_interval=active.config.checkpoint_interval,
            max_epoch_length=active.config.max_epoch_length,
            pending_reconfigurations=len(active.pending_reconfigurations),
            reconfigs_adopted=machine.reconfigs_adopted,
            retired=machine.retired,
        )

    return StateMachineStatus(
        node_id=machine.my_config.id,
        low_watermark=low,
        high_watermark=high,
        epoch_tracker=tracker_status,
        client_windows=clients,
        buckets=buckets,
        checkpoints=checkpoints,
        bucket_backlog=backlog,
        bucket_imbalance=imbalance,
        network_config=config_status,
    )


@dataclass
class PeerLinkStatus:
    """One peer's outbound channel (runtime/transport.py counters)."""

    peer_id: int
    enqueued: int
    sent: int
    dropped_overflow: int
    dropped_closed: int
    send_failures: int
    connect_failures: int
    connects: int
    queue_depth: int


@dataclass
class TransportStatus:
    """Snapshot of a TcpTransport's drop/retry accounting."""

    node_id: int
    dropped_unknown: int
    peers: list = field(default_factory=list)  # [PeerLinkStatus]

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, default=str)

    def pretty(self) -> str:
        lines = [f"=== Transport (node {self.node_id}) ==="]
        if self.dropped_unknown:
            lines.append(f"  dropped (unknown peer): {self.dropped_unknown}")
        for peer in self.peers:
            drops = peer.dropped_overflow + peer.dropped_closed
            lines.append(
                f"  peer {peer.peer_id}: sent={peer.sent}/{peer.enqueued} "
                f"queued={peer.queue_depth} dropped={drops} "
                f"send_failures={peer.send_failures} "
                f"connects={peer.connects} "
                f"(failed {peer.connect_failures})"
            )
        return "\n".join(lines)


def transport_status(transport) -> TransportStatus:
    """Snapshot a runtime.transport.TcpTransport."""
    counters = transport.counters()
    return TransportStatus(
        node_id=transport.node_id,
        dropped_unknown=counters["dropped_unknown"],
        peers=[
            PeerLinkStatus(peer_id=peer_id, **stats)
            for peer_id, stats in sorted(counters["peers"].items())
        ],
    )


@dataclass
class TransferStatus:
    """Snapshot of a runtime.transfer.TransferEngine: fetch progress on
    the fetcher side, cached anchors on the donor side, and the evidence
    counters the chaos audits read."""

    node_id: int
    phase: str
    target_seq_no: int | None
    donor: int | None
    chunks_received: int
    total_chunks: int | None
    cached_snapshots: list = field(default_factory=list)
    counters: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, default=str)

    def pretty(self) -> str:
        lines = [f"=== State transfer (node {self.node_id}) ==="]
        if self.phase == "idle":
            lines.append("  idle")
        else:
            total = self.total_chunks if self.total_chunks is not None else "?"
            lines.append(
                f"  {self.phase} target=seq {self.target_seq_no} "
                f"donor={self.donor} chunks={self.chunks_received}/{total}"
            )
        if self.cached_snapshots:
            lines.append(
                "  servable anchors: "
                + ", ".join(str(s) for s in self.cached_snapshots)
            )
        interesting = {k: v for k, v in sorted(self.counters.items()) if v}
        if interesting:
            lines.append(
                "  "
                + " ".join(f"{k}={v}" for k, v in interesting.items())
            )
        return "\n".join(lines)


def transfer_status(engine) -> TransferStatus:
    """Snapshot a runtime.transfer.TransferEngine."""
    snap = engine.status()
    return TransferStatus(
        node_id=engine.node_id,
        phase=snap["phase"],
        target_seq_no=snap["target_seq_no"],
        donor=snap["donor"],
        chunks_received=snap["chunks_received"],
        total_chunks=snap["total_chunks"],
        cached_snapshots=snap["cached_snapshots"],
        counters=snap["counters"],
    )


@dataclass
class AppStatus:
    """Snapshot of the app commit stream (app/stream.py): the applied
    and enqueued frontiers, queue pressure, and read-barrier traffic —
    the user-visible side of the node.  Also published as ``app.json``
    by the cluster worker."""

    node_id: int
    applied_seq: int
    applied_index: int
    enqueued_seq: int
    enqueued_index: int
    queue_len: int
    queue_depth: int
    waiters: int
    installs: int
    snapshots: int

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, default=str)

    def pretty(self) -> str:
        lines = [f"=== App (node {self.node_id}) ==="]
        lines.append(
            f"  applied: index {self.applied_index} @ seq "
            f"{self.applied_seq} (enqueued: index {self.enqueued_index} "
            f"@ seq {self.enqueued_seq})"
        )
        lines.append(
            f"  queue: {self.queue_len}/{self.queue_depth} "
            f"waiters={self.waiters} installs={self.installs} "
            f"snapshots_retained={self.snapshots}"
        )
        return "\n".join(lines)


def app_status(stream, node_id: int | None = None) -> AppStatus:
    """Snapshot an app.stream.CommitStream."""
    snap = stream.status()
    return AppStatus(
        node_id=node_id if node_id is not None else stream.node_id,
        applied_seq=snap["applied_seq"],
        applied_index=snap["applied_index"],
        enqueued_seq=snap["enqueued_seq"],
        enqueued_index=snap["enqueued_index"],
        queue_len=snap["queue_len"],
        queue_depth=snap["queue_depth"],
        waiters=snap["waiters"],
        installs=snap["installs"],
        snapshots=snap["snapshots"],
    )


@dataclass
class BreakerStatus:
    state: str
    consecutive_failures: int
    failures: int
    successes: int
    trips: int
    probes: int


@dataclass
class CryptoPlaneStatus:
    """Device-health snapshot of a digest or signature plane: how much
    work the device did vs. was rescued/fallen back to the host, and what
    the circuit breaker thinks of the device right now."""

    plane: str
    flushes: int
    device_errors: int
    fallback_work: int
    device_timeouts: int = 0
    rescued_digests: int = 0
    # Speculative admission (SpeculativeSignaturePlane / ingress): how
    # many verdicts are still outstanding and how many admitted requests
    # were evicted on a false verdict.  Zero for non-speculative planes.
    speculative_depth: int = 0
    speculative_evictions: int = 0
    breaker: BreakerStatus | None = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, default=str)

    def pretty(self) -> str:
        lines = [f"=== Crypto plane ({self.plane}) ==="]
        lines.append(
            f"  flushes={self.flushes} device_errors={self.device_errors} "
            f"timeouts={self.device_timeouts} "
            f"fallback={self.fallback_work} rescued={self.rescued_digests}"
        )
        if self.speculative_depth or self.speculative_evictions:
            lines.append(
                f"  speculative: depth={self.speculative_depth} "
                f"evictions={self.speculative_evictions}"
            )
        if self.breaker is not None:
            b = self.breaker
            lines.append(
                f"  breaker: {b.state} "
                f"(consecutive_failures={b.consecutive_failures}, "
                f"trips={b.trips}, probes={b.probes}, "
                f"{b.successes} ok / {b.failures} failed)"
            )
        return "\n".join(lines)


def crypto_plane_status(plane) -> CryptoPlaneStatus:
    """Snapshot a testengine crypto plane (CoalescingHashPlane,
    AsyncKernelHashPlane, SignaturePlane, AsyncSignaturePlane, or
    SpeculativeSignaturePlane)."""
    breaker = getattr(plane, "breaker", None)
    breaker_status = None
    if breaker is not None:
        breaker_status = BreakerStatus(
            state=breaker.state,
            consecutive_failures=breaker.consecutive_failures,
            failures=breaker.failures,
            successes=breaker.successes,
            trips=breaker.trips,
            probes=breaker.probes,
        )
    return CryptoPlaneStatus(
        plane=type(plane).__name__,
        flushes=len(plane.flush_sizes),
        device_errors=getattr(plane, "device_errors", 0),
        device_timeouts=getattr(plane, "device_timeouts", 0),
        fallback_work=getattr(plane, "fallback_digests", 0)
        or getattr(plane, "fallback_verifies", 0),
        rescued_digests=getattr(plane, "rescued_digests", 0),
        speculative_depth=getattr(plane, "speculative_depth", 0),
        speculative_evictions=getattr(plane, "speculative_evictions", 0),
        breaker=breaker_status,
    )


@dataclass
class MetricsStatus:
    """Snapshot of the obsv metrics registry, folded into the same
    to_json()/pretty() idiom as the tracker snapshots.  ``families`` is
    the registry's snapshot(): name -> {kind, help, series}."""

    enabled: bool
    families: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, default=str)

    def pretty(self) -> str:
        lines = ["=== Metrics ==="]
        if not self.enabled:
            lines.append("  (observability disabled)")
            return "\n".join(lines)
        if not self.families:
            lines.append("  (no metrics recorded)")
        for name, family in self.families.items():
            for entry in family["series"]:
                labels = entry["labels"]
                label_str = (
                    "{"
                    + ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                    + "}"
                    if labels
                    else ""
                )
                if family["kind"] == "histogram":
                    count = entry["count"]
                    mean = entry["sum"] / count if count else 0.0
                    lines.append(
                        f"  {name}{label_str}: count={count} mean={mean:.6f}"
                    )
                else:
                    lines.append(f"  {name}{label_str}: {entry['value']}")
        return "\n".join(lines)


def metrics_status(registry=None) -> MetricsStatus:
    """Snapshot an obsv Registry (default: the hooks-installed one)."""
    from .obsv import hooks

    if registry is None:
        registry = hooks.metrics
    if registry is None:
        return MetricsStatus(enabled=False)
    return MetricsStatus(enabled=True, families=registry.snapshot())


def pretty(status: StateMachineStatus) -> str:
    """ASCII dashboard (reference: status/status.go:141-296)."""
    lines = [
        "===========================================",
        f"NodeID={status.node_id}, "
        f"LowWatermark={status.low_watermark}, "
        f"HighWatermark={status.high_watermark}, "
        f"Epoch={status.epoch_tracker.number if status.epoch_tracker else '?'} "
        f"({status.epoch_tracker.state if status.epoch_tracker else '?'})",
        "===========================================",
        "",
    ]
    if status.network_config is not None:
        nc = status.network_config
        retired = " RETIRED" if nc.retired else ""
        lines.append("=== Network Config ===")
        lines.append(
            f"  epoch {nc.epoch} @seq {nc.first_seq}: nodes={nc.nodes} "
            f"f={nc.f} buckets={nc.number_of_buckets} "
            f"ci={nc.checkpoint_interval}{retired}"
        )
        lines.append(
            f"  reconfigs: pending={nc.pending_reconfigurations} "
            f"adopted={nc.reconfigs_adopted}"
        )
        lines.append("")
    if status.buckets:
        lines.append("=== Buckets ===")
        lines.append("  (.=unalloc a=alloc q=pending r=ready "
                     "Q=preprepared P=prepared C=committed)")
        for bucket in status.buckets:
            marker = "*" if bucket.leader else " "
            lines.append(
                f"  {marker}bucket {bucket.id}: {''.join(bucket.sequences)}"
            )
        if status.bucket_backlog:
            lines.append(
                "  backlog: "
                + " ".join(str(n) for n in status.bucket_backlog)
                + f"  (imbalance max/median {status.bucket_imbalance:.2f})"
            )
        lines.append("")
    if status.checkpoints:
        lines.append("=== Checkpoints ===")
        for cp in status.checkpoints:
            flags = "".join(
                c
                for c, on in (
                    ("N", cp.net_quorum),
                    ("L", cp.local_decision),
                    ("S", cp.stable),
                )
                if on
            )
            lines.append(
                f"  seq {cp.seq_no}: agreements={cp.max_agreements} [{flags}]"
            )
        lines.append("")
    if status.client_windows:
        lines.append("=== Clients ===")
        lines.append("  (A=acked W=weak S=strong R=ready C=committed)")
        for client in status.client_windows:
            window = "".join(c or "_" for c in client.allocated)
            lines.append(
                f"  client {client.client_id} "
                f"[{client.low_watermark}..{client.high_watermark}] "
                f"ready@{client.next_ready_mark}: {window}"
            )
        lines.append("")
    if status.epoch_tracker:
        et = status.epoch_tracker
        if et.epoch_changes or et.echos or et.readies or et.suspicions:
            lines.append("=== Epoch Transition ===")
            for ec in et.epoch_changes:
                lines.append(f"  change from {ec.source}: {ec.msgs}")
            if et.echos:
                lines.append(f"  echos: {et.echos}")
            if et.readies:
                lines.append(f"  readies: {et.readies}")
            if et.suspicions:
                lines.append(f"  suspicions: {et.suspicions}")
    return "\n".join(lines)
