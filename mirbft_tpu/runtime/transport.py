"""TCP transport: the Link contract over real sockets (the DCN path).

The reference deliberately ships no transport — the entire contract is
``Link.Send(dest, msg)``, fire-and-forget and unreliable-by-assumption
(reference: processor.go:23-25); the protocol tolerates loss via
retransmit ticks.  This module is the consumer-side implementation for
multi-host deployments: length-prefixed frames of the deterministic wire
codec over persistent TCP connections between replica hosts.

Fault model (the hardening layer over the bare Link contract):

- ``send`` never blocks the caller: frames enqueue onto a bounded
  per-peer outbound queue drained by a dedicated sender thread, so one
  stalled peer cannot block broadcast to the others.
- The sender thread (re)connects lazily and retries failed connections
  with exponential backoff + full jitter (resilience.Backoff), so a
  restarted peer is re-dialed automatically and a recovering peer is not
  met with a connection storm from the whole mesh.
- Queue overflow drops the *oldest* frame (newest protocol messages
  supersede older ones); every drop, failure, and reconnect is counted
  and surfaced via ``counters()`` / ``status.transport_status`` so chaos
  runs can assert on observed fault counts.
- ``close(drain_timeout=...)`` optionally flushes queued frames over
  live connections before tearing down, and shuts the write side down
  first so peers observe a clean EOF rather than a reset.

Authentication note: the reference makes source authentication the
caller's job (mirbft.go:297-301).  Frames carry a claimed source id; a
production deployment wraps the sockets in mutually-authenticated TLS and
checks the claim against the peer certificate.  In-process and test use
trust the header, exactly like the reference's test transports.

Frame format: [u32 little-endian total length][varint source][pb.Msg].

Clock-sync hello: the first frame on every freshly dialed connection is
a hello — the reserved source id ``_HELLO_SRC`` followed by the dialer's
node id and its ``perf_counter_ns`` monotonic anchor.  The receiver
records ``local_anchor - remote_anchor`` per peer (``clock_offsets()``),
which obsv/merge.py uses to align per-node trace timelines.  The
estimate absorbs the hello's one-way network latency; on a single host
CLOCK_MONOTONIC is system-wide so it is exact up to that latency.  Old
frames are unaffected: a hello is just a frame whose source id no real
node can carry.
"""

from __future__ import annotations

import collections
import random
import select
import socket
import struct
import threading
import time

from .. import pb, wire
from ..obsv import hooks
from ..obsv.bqueue import QueueTelemetry
from ..resilience import Backoff
from .processor import Link

_LEN = struct.Struct("<I")
_LEN_PLACEHOLDER = bytes(_LEN.size)
_MAX_FRAME = 64 * 1024 * 1024

# Sender-side coalescing: one wakeup drains up to this many payload bytes
# from the peer queue into a single sendall.  Bounds the transient buffer
# a deep queue can force while still amortizing syscalls over bursts.
_COALESCE_BYTES = 512 * 1024

# Count buckets for mirbft_transport_frames_per_write (frames, not
# seconds — powers of two up to the 1024-frame queue depth).
_FRAMES_PER_WRITE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _set_nodelay(conn: socket.socket) -> None:
    """Disable Nagle: consensus frames are latency-critical and the
    sender already coalesces bursts explicitly, so the kernel delaying
    small writes only adds round-trip stalls.  Applied to both dialed and
    accepted sockets — Nagle is per-direction, so one side is not enough."""
    try:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # non-TCP or platform oddity: coalescing still works

# Reserved frame source id marking a clock-sync hello.  Real node ids are
# small integers assigned by NetworkConfig; 2**62 keeps the varint within
# the codec's 64-bit bound while staying unmistakably out of range.
_HELLO_SRC = 1 << 62

# Reserved frame source id marking a client proposal: the payload after
# the id is a bare pb.Request (not a pb.Msg), delivered to node.propose.
# This keeps every socket a client endpoint needs inside this module —
# loadgen and the cluster supervisor submit through a TcpTransport
# instead of opening raw sockets of their own (lint rule W9).
_PROPOSE_SRC = (1 << 62) + 1

# Reserved frame source id marking a state-transfer frame: the payload
# after the id is an opaque transfer body (runtime/transfer.py codec, not
# a pb.Msg), delivered to the sink installed via set_transfer_sink.
# Snapshot chunks ride the same sockets and per-peer queues as protocol
# traffic, so partitions/latency/adversary seams apply to them for free.
_XFER_SRC = (1 << 62) + 2


class LinkLatency:
    """Emulated one-way link latency: frames to the peer are held on the
    sender queue until ``delay + U(0, jitter)`` has elapsed since enqueue.
    Deterministic per (seed, peer): chaos/bench runs with the same seed
    see the same jitter sequence.  Emulation happens before the real
    socket write, so it composes with (and adds to) genuine network
    latency — loopback clusters gain a WAN rung without root or ``tc``."""

    __slots__ = ("delay_s", "jitter_s", "_rng")

    def __init__(self, delay_s: float, jitter_s: float = 0.0, seed: int = 0):
        if delay_s < 0 or jitter_s < 0:
            raise ValueError("latency delay/jitter must be >= 0")
        self.delay_s = delay_s
        self.jitter_s = jitter_s
        self._rng = random.Random(seed)

    def due(self, now: float) -> float:
        if self.jitter_s:
            return now + self.delay_s + self._rng.random() * self.jitter_s
        return now + self.delay_s


def _hello_frame(node_id: int, link_auth=None, peer_id: int = -1) -> bytes:
    payload = (
        wire.encode_varint(_HELLO_SRC)
        + wire.encode_varint(node_id)
        + wire.encode_varint(time.perf_counter_ns())
    )
    if link_auth is not None:
        payload = link_auth.seal(peer_id, payload)
    return _LEN.pack(len(payload)) + payload


def _frame_outcome(outcome: str, n: int = 1) -> None:
    """Mirror the per-channel counters into the metrics registry (the
    channel attributes remain the source of truth for counters())."""
    if hooks.enabled:
        hooks.metrics.counter(
            "mirbft_transport_frames_total", outcome=outcome
        ).inc(n)


def _dial_outcome(outcome: str) -> None:
    if hooks.enabled:
        hooks.metrics.counter(
            "mirbft_transport_reconnects_total", outcome=outcome
        ).inc()


class TransportFault:
    """Fault-injection seam consulted on the transport's hot paths.

    Chaos drivers (chaos/live.py) subclass this and assign an instance to
    ``TcpTransport.fault``; production leaves the attribute ``None`` so
    the seam costs one attribute read per send/dial.  Both hooks run on
    transport-internal threads and must not block.
    """

    def on_dial(self, peer_id: int) -> bool:
        """Return False to fail this dial attempt (counted as a
        ``faulted`` reconnect outcome; the normal backoff applies)."""
        return True

    def on_send(self, peer_id: int, frame: bytes) -> bool:
        """Return False to drop this frame before it is enqueued
        (counted as a ``dropped_fault`` frame outcome)."""
        return True


class _PeerChannel:
    """Outbound lane to one peer: a bounded frame queue plus the sender
    thread that owns connecting, retrying, and draining it."""

    def __init__(self, transport: "TcpTransport", peer_id: int):  # holds: _lock
        self.transport = transport
        self.peer_id = peer_id
        # Without latency emulation the deque holds bare frames; with a
        # LinkLatency installed it holds (due_monotonic, frame) pairs and
        # the sender drains only frames whose due time has passed.
        self.queue: collections.deque = collections.deque()  # guarded-by: cv
        self.latency: LinkLatency | None = transport._link_latency.get(
            peer_id
        )  # guarded-by: cv
        self.cv = threading.Condition()
        self.closed = False  # guarded-by: cv
        self._drain_deadline = 0.0  # guarded-by: cv
        self.backoff = Backoff(
            base=transport.backoff_base, cap=transport.backoff_cap
        )
        # Backpressure telemetry (obsv/bqueue.py): the deque cannot be
        # swapped for a BoundedQueue (latency pairs + drop-oldest +
        # coalesced drain under one cv), so the channel drives the
        # QueueTelemetry handle at its own put/drain points.  Wait is
        # head-of-line age: the stamp of the oldest queued frame,
        # observed when a drain finally picks the head up.
        self.telemetry = QueueTelemetry(f"transport.peer{peer_id}")
        self._head_enqueued_at = 0.0  # guarded-by: cv
        # Drop/retry accounting (read via TcpTransport.counters()).
        self.enqueued = 0  # guarded-by: cv
        self.sent = 0  # guarded-by: cv
        self.dropped_overflow = 0  # guarded-by: cv
        self.dropped_closed = 0  # guarded-by: cv
        self.send_failures = 0  # guarded-by: cv
        self.connect_failures = 0  # guarded-by: cv
        self.connects = 0  # guarded-by: cv
        self.thread = threading.Thread(
            target=self._run,
            name=f"tcp-send-{transport.node_id}-{peer_id}",
            daemon=True,
        )
        self.thread.start()

    def enqueue(self, frame: bytes) -> None:
        with self.cv:
            if self.closed:
                self.dropped_closed += 1
                _frame_outcome("dropped_closed")
                return
            if len(self.queue) >= self.transport.queue_depth:
                self.queue.popleft()
                self.dropped_overflow += 1
                _frame_outcome("dropped_overflow")
                self.telemetry.saturated()
            lat = self.latency
            if lat is None:
                self.queue.append(frame)
            else:
                self.queue.append((lat.due(time.monotonic()), frame))
            self.enqueued += 1
            _frame_outcome("enqueued")
            if hooks.enabled:
                if len(self.queue) == 1:
                    self._head_enqueued_at = time.perf_counter()
                self.telemetry.depth(len(self.queue))
            self.cv.notify()

    def close(self, drain_timeout: float) -> None:
        with self.cv:
            self.closed = True
            self._drain_deadline = time.monotonic() + drain_timeout
            self.cv.notify()

    # -- sender thread -------------------------------------------------------

    def _run(self) -> None:
        frames: list[bytes] = []
        while True:
            with self.cv:
                while not self.queue and not self.closed:
                    self.cv.wait()
                if self.closed and (
                    not self.queue
                    or time.monotonic() >= self._drain_deadline
                ):
                    self.dropped_closed += len(self.queue)
                    _frame_outcome("dropped_closed", len(self.queue))
                    self.queue.clear()
                    return
                lat = self.latency
                if lat is not None and not self.closed:
                    # Emulated link latency: hold the head frame until its
                    # due time (closing drains immediately — teardown must
                    # not wait out a WAN profile).
                    wait = self.queue[0][0] - time.monotonic()
                    if wait > 0:
                        self.cv.wait(timeout=wait)
                        continue
                # Coalesce: drain the burst (up to a byte budget) so many
                # queued frames cost one sendall instead of one syscall
                # each.  Frames left past the budget go on the next wakeup.
                frames.clear()
                budget = _COALESCE_BYTES
                if lat is None:
                    while self.queue and budget > 0:
                        frame = self.queue.popleft()
                        frames.append(frame)
                        budget -= len(frame)
                else:
                    now = time.monotonic()
                    while self.queue and budget > 0 and (
                        self.closed or self.queue[0][0] <= now
                    ):
                        frame = self.queue.popleft()[1]
                        frames.append(frame)
                        budget -= len(frame)
                    if not frames:
                        continue  # head not due yet (raced with enqueue)
                if hooks.enabled and frames:
                    now = time.perf_counter()
                    if self._head_enqueued_at:
                        self.telemetry.wait(
                            max(0.0, now - self._head_enqueued_at)
                        )
                    # Frames left past the coalesce budget become the
                    # new head; their age restarts at this drain.
                    self._head_enqueued_at = now if self.queue else 0.0
                    self.telemetry.depth(len(self.queue))
            entry = self._ensure_connected()
            if entry is None:
                # Shut down while connecting/backing off: the burst (and
                # the rest of the queue, handled above) is dropped.
                with self.cv:
                    self.dropped_closed += len(frames)
                    _frame_outcome("dropped_closed", len(frames))
                continue
            conn, send_lock = entry
            buf = frames[0] if len(frames) == 1 else b"".join(frames)
            try:
                # Peer-death probe before committing the whole burst to
                # one write: a FIN/RST already queued on the socket means
                # the write would "succeed" into a dead connection and a
                # coalesced burst would vanish in a single syscall (the
                # old frame-at-a-time loop got per-frame error probes for
                # free).  A zero-timeout readability check + MSG_PEEK is
                # cheap per burst and lets the burst requeue *unsent*.
                # (select, not MSG_DONTWAIT: the dialed socket is in
                # timeout mode, where a bare recv blocks in Python's
                # select loop regardless of recv flags.)
                readable, _, _ = select.select([conn], [], [], 0)
                if readable and conn.recv(1, socket.MSG_PEEK) == b"":
                    raise OSError("peer closed connection")
                with send_lock:
                    conn.sendall(buf)
            except OSError:
                with self.cv:
                    self.send_failures += 1
                _frame_outcome("send_failure")
                self._drop_conn(entry)
                # Put the burst back at the head, oldest first, so
                # delivery resumes in order after reconnect; whatever
                # would overflow is dropped from the burst's tail.
                with self.cv:
                    space = self.transport.queue_depth - len(self.queue)
                    keep = frames[: max(space, 0)]
                    for frame in reversed(keep):
                        # Already-due placeholder on latency links: the
                        # emulated delay was served before the first try.
                        self.queue.appendleft(
                            frame if self.latency is None else (0.0, frame)
                        )
                    dropped = len(frames) - len(keep)
                    if dropped:
                        self.dropped_overflow += dropped
                        _frame_outcome("dropped_overflow", dropped)
                continue
            with self.cv:
                self.sent += len(frames)
                _frame_outcome("sent", len(frames))
            if hooks.enabled:
                hooks.metrics.histogram(
                    "mirbft_transport_frames_per_write",
                    buckets=_FRAMES_PER_WRITE_BUCKETS,
                ).observe(len(frames))

    def _ensure_connected(self):
        """Return the live (socket, lock) entry for this peer, dialing with
        backoff until connected or the channel/transport closes."""
        transport = self.transport
        while True:
            with transport._lock:
                entry = transport._conns.get(self.peer_id)
                address = transport._peers.get(self.peer_id)
            if entry is not None:
                return entry
            with self.cv:
                chan_closed = self.closed
            closing = transport._closed.is_set() or chan_closed
            if closing or address is None:
                # No new connections once closing; draining only flushes
                # over connections that already exist.
                return None
            fault = transport.fault
            if fault is not None and not fault.on_dial(self.peer_id):
                _dial_outcome("faulted")
                delay = self.backoff.next()
                with self.cv:
                    self.connect_failures += 1
                    if not self.closed:
                        self.cv.wait(timeout=delay)
                continue
            try:
                conn = socket.create_connection(
                    address, timeout=transport.dial_timeout
                )
            except TimeoutError:
                # Dial deadline: a peer that accepts SYNs but never
                # completes (or a black-holing firewall) cannot pin the
                # sender thread longer than dial_timeout per attempt.
                _dial_outcome("timeout")
                delay = self.backoff.next()
                with self.cv:
                    self.connect_failures += 1
                    if not self.closed:
                        self.cv.wait(timeout=delay)
                continue
            except OSError:
                _dial_outcome("failed")
                delay = self.backoff.next()
                with self.cv:
                    self.connect_failures += 1
                    if not self.closed:
                        self.cv.wait(timeout=delay)
                continue
            self.backoff.reset()
            _set_nodelay(conn)
            entry = (conn, threading.Lock())
            with transport._lock:
                if transport._closed.is_set():
                    conn.close()
                    return None
                existing = transport._conns.setdefault(self.peer_id, entry)
            if existing is not entry:
                conn.close()
                entry = existing
            else:
                with self.cv:
                    self.connects += 1
                _dial_outcome("connected")
                # First frame on a fresh connection: the clock-sync
                # hello (monotonic anchor for trace alignment).  Best
                # effort — a failed hello just means the sender loop
                # discovers the dead socket on the next frame.
                conn_, send_lock = entry
                try:
                    with send_lock:
                        conn_.sendall(
                            _hello_frame(
                                transport.node_id,
                                transport.link_auth,
                                self.peer_id,
                            )
                        )
                except OSError:
                    pass
            return entry

    def _drop_conn(self, entry) -> None:
        transport = self.transport
        with transport._lock:
            if transport._conns.get(self.peer_id) is entry:
                del transport._conns[self.peer_id]
        entry[0].close()


class TcpTransport:
    """One replica's endpoint: a listening socket delivering inbound
    messages to the local Node, and queue-backed outbound links with
    automatic reconnection."""

    def __init__(
        self,
        node_id: int,
        host: str = "127.0.0.1",
        port: int = 0,
        queue_depth: int = 1024,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        dial_timeout: float = 5.0,
        link_auth=None,
    ):
        self.node_id = node_id
        # Per-link MAC authenticator (crypto/mac.LinkAuthenticator) for
        # the replica plane: node/hello/transfer frames carry a sealed
        # tag verified (and stripped) at ingress, keyed by the claimed
        # source.  The client propose lane is exempt — client requests
        # are authenticated by Ed25519 signatures, not link MACs (the
        # PBFT split: signatures for requests/certificates, MACs for
        # replica channels).  None disables authentication entirely.
        self.link_auth = link_auth
        # kind -> count of frames rejected at the MAC check; mirrored to
        # mirbft_mac_rejections_total (chaos evidence + dashboards).
        self.mac_rejections: dict[str, int] = {}  # guarded-by: _lock
        self.queue_depth = queue_depth
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.dial_timeout = dial_timeout
        # Fault-injection seam (TransportFault); None in production.
        self.fault: TransportFault | None = None
        # peer id -> LinkLatency for emulated WAN links (see
        # set_link_latency); empty in production.
        self._link_latency: dict[int, LinkLatency] = {}  # guarded-by: _lock
        # Frame-encoder scratch: per-thread bytearray (multiple processor
        # stage threads may send concurrently) plus the precomputed source
        # id varint every outbound frame starts with.
        self._scratch = threading.local()
        self._src_prefix = wire.encode_varint(node_id)
        self._node = None
        # Inbound state-transfer frames (see set_transfer_sink); None
        # until a transfer engine attaches, and such frames drop.
        self._transfer_sink = None
        # Inbound client-lane override (see set_propose_sink); None
        # routes proposes straight to the node.
        self._propose_sink = None
        self._peers: dict[int, tuple] = {}  # guarded-by: _lock
        # id -> (socket, per-connection send lock).  The transport-wide
        # _lock guards only the maps; each peer's sends run on its own
        # sender thread so one stalled peer cannot block the others.
        self._conns: dict[int, tuple[socket.socket, threading.Lock]] = {}  # guarded-by: _lock
        self._channels: dict[int, _PeerChannel] = {}  # guarded-by: _lock
        # Sends to peers never registered via connect(): dropped, counted.
        self.dropped_unknown = 0  # guarded-by: _lock
        # Frames suppressed by the fault seam (chaos runs only).
        self.dropped_fault = 0  # guarded-by: _lock
        # peer id -> (local perf_counter_ns - peer perf_counter_ns),
        # estimated from the clock-sync hello on each inbound connection.
        self._clock_offsets: dict[int, int] = {}  # guarded-by: _lock
        # Accepted inbound sockets.  close() must shutdown+close these too:
        # leaving them open keeps their read threads blocked in recv, keeps
        # the port occupied past a rebind, and — worse — lets a "closed"
        # transport keep delivering frames to its sink.
        self._accepted: set[socket.socket] = set()  # guarded-by: _lock
        # Reader threads for accepted sockets, tracked so close() can join
        # them: a daemon thread parked in recv survives close() otherwise,
        # and 100 start/stop cycles then leak 100 threads.
        self._read_threads: set[threading.Thread] = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._closed = threading.Event()

        self._server = socket.create_server((host, port))
        self.address = self._server.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"tcp-accept-{node_id}",
            daemon=True,
        )
        self._accept_thread.start()

    # -- wiring ----------------------------------------------------------------

    def serve(self, node) -> None:
        """Attach the local Node; inbound frames become node.step calls."""
        self._node = node

    def connect(self, peer_id: int, address: tuple) -> None:
        """Register a peer's address; connections are opened lazily on the
        first send and re-dialed with backoff after failures."""
        with self._lock:
            self._peers[peer_id] = tuple(address)

    def set_link_latency(
        self,
        peer_id: int,
        delay_s: float,
        jitter_s: float = 0.0,
        seed: int = 0,
    ) -> None:
        """Install emulated one-way latency on the outbound link to
        ``peer_id`` (``delay_s`` fixed + uniform jitter up to
        ``jitter_s``, deterministic per seed).  Takes effect for frames
        enqueued after the call; frames already queued keep whatever
        representation they were enqueued with, so set latency before
        traffic starts (the cluster runner configures links at boot)."""
        lat = LinkLatency(delay_s, jitter_s, seed=seed ^ (peer_id << 8))
        with self._lock:
            self._link_latency[peer_id] = lat
            channel = self._channels.get(peer_id)
        if channel is not None:
            with channel.cv:
                if channel.queue:
                    raise RuntimeError(
                        "set_link_latency on a link with queued frames"
                    )
                channel.latency = lat
                channel.cv.notify()

    # -- outbound --------------------------------------------------------------

    def link(self) -> Link:
        transport = self

        class _TcpLink(Link):
            def send(self, dest: int, msg: pb.Msg) -> None:
                transport._send(dest, msg)

        return _TcpLink()

    def _channel(self, dest: int) -> _PeerChannel | None:
        with self._lock:
            channel = self._channels.get(dest)
            if channel is not None:
                return channel
            if dest not in self._peers or self._closed.is_set():
                return None
            channel = _PeerChannel(self, dest)
            self._channels[dest] = channel
            return channel

    def _encode_frame(self, msg: pb.Msg) -> bytes:
        """Frame one message reusing a per-thread bytearray scratch: the
        naive ``_LEN.pack(len(p)) + p`` spelling allocates (and copies)
        two intermediate bytes objects per message; here the length
        placeholder is patched in place and only the final immutable
        ``bytes`` (required — frames outlive the call on peer queues) is
        allocated."""
        buf = getattr(self._scratch, "buf", None)
        if buf is None:
            buf = self._scratch.buf = bytearray()
        del buf[:]
        buf += _LEN_PLACEHOLDER
        buf += self._src_prefix
        buf += pb.encode(msg)
        _LEN.pack_into(buf, 0, len(buf) - _LEN.size)
        return bytes(buf)

    def _sealed_frame(self, dest: int, msg: pb.Msg) -> bytes:
        """MAC-authenticated framing: the tag covers source id + body and
        is keyed per destination link, so the scratch fast path (which is
        destination-independent) does not apply."""
        payload = self.link_auth.seal(
            dest, self._src_prefix + pb.encode(msg)
        )
        return _LEN.pack(len(payload)) + payload

    def _send(self, dest: int, msg: pb.Msg) -> None:
        if self.link_auth is not None:
            frame = self._sealed_frame(dest, msg)
        else:
            frame = self._encode_frame(msg)
        fault = self.fault
        if fault is not None and not fault.on_send(dest, frame):
            with self._lock:
                self.dropped_fault += 1
            _frame_outcome("dropped_fault")
            return  # injected loss: indistinguishable from the network's
        channel = self._channel(dest)
        if channel is None:
            with self._lock:
                self.dropped_unknown += 1
            _frame_outcome("dropped_unknown")
            return  # unknown peer: dropped, like any unreachable host
        channel.enqueue(frame)

    def propose(self, dest: int, request: pb.Request) -> None:
        """Client-side submission: frame a bare pb.Request under the
        reserved ``_PROPOSE_SRC`` id and enqueue it to ``dest`` (which
        must be ``connect``-ed first).  The receiving transport hands the
        request to its node's ``propose`` — the open-loop load generator
        and the cluster supervisor submit through this instead of opening
        sockets of their own.  Fire-and-forget like ``send``: duplicate
        submission on timeout is the client model, and the protocol's
        dedup absorbs it."""
        payload = (
            wire.encode_varint(_PROPOSE_SRC)
            + wire.encode_varint(self.node_id)
            + pb.encode(request)
        )
        frame = _LEN.pack(len(payload)) + payload
        channel = self._channel(dest)
        if channel is None:
            with self._lock:
                self.dropped_unknown += 1
            _frame_outcome("dropped_unknown")
            return
        channel.enqueue(frame)

    def send_transfer(self, dest: int, body: bytes) -> None:
        """State-transfer lane: frame an opaque transfer body (the
        runtime/transfer.py chunk codec) under the reserved ``_XFER_SRC``
        id and enqueue it to ``dest``.  The receiving transport hands
        ``(sender_id, body)`` to the sink installed via
        ``set_transfer_sink``.  Fire-and-forget like ``send``: the
        transfer engine owns timeouts, retry, and donor failover."""
        payload = (
            wire.encode_varint(_XFER_SRC)
            + wire.encode_varint(self.node_id)
            + body
        )
        if self.link_auth is not None:
            payload = self.link_auth.seal(dest, payload)
        frame = _LEN.pack(len(payload)) + payload
        fault = self.fault
        if fault is not None and not fault.on_send(dest, frame):
            with self._lock:
                self.dropped_fault += 1
            _frame_outcome("dropped_fault")
            return
        channel = self._channel(dest)
        if channel is None:
            with self._lock:
                self.dropped_unknown += 1
            _frame_outcome("dropped_unknown")
            return
        channel.enqueue(frame)

    def set_propose_sink(self, sink) -> None:
        """Route inbound client-lane requests through ``sink(request)``
        instead of ``node.propose`` — the speculative ingress verify
        stage installs itself here (runtime/ingress.py)."""
        self._propose_sink = sink

    def set_transfer_sink(self, sink) -> None:
        """Install the inbound state-transfer handler: ``sink(sender_id,
        body)`` is called on a transport read thread for every
        ``_XFER_SRC`` frame and must not block (the transfer engine
        queues the frame and returns)."""
        self._transfer_sink = sink

    def counters(self) -> dict:
        """Per-peer drop/retry accounting for dashboards and chaos gates
        (see status.transport_status for the dataclass view)."""
        with self._lock:
            channels = dict(self._channels)
            connected = set(self._conns)
            dropped_unknown = self.dropped_unknown
            dropped_fault = self.dropped_fault
            mac_rejections = dict(self.mac_rejections)
        peers = {}
        for peer_id, ch in channels.items():
            with ch.cv:
                peers[peer_id] = {
                    "connected": peer_id in connected,
                    "queue_depth": len(ch.queue),
                    "enqueued": ch.enqueued,
                    "sent": ch.sent,
                    "dropped_overflow": ch.dropped_overflow,
                    "dropped_closed": ch.dropped_closed,
                    "send_failures": ch.send_failures,
                    "connect_failures": ch.connect_failures,
                    "connects": ch.connects,
                }
        return {
            "dropped_unknown": dropped_unknown,
            "dropped_fault": dropped_fault,
            "mac_rejections": mac_rejections,
            "peers": peers,
        }

    # -- inbound ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # closed
            _set_nodelay(conn)
            thread = threading.Thread(
                target=self._read_loop,
                args=(conn,),
                name=f"tcp-read-{self.node_id}",
                daemon=True,
            )
            with self._lock:
                if self._closed.is_set():
                    conn.close()
                    return
                self._accepted.add(conn)
                self._read_threads.add(thread)
            thread.start()

    def _read_loop(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                header = self._read_exact(conn, _LEN.size)
                if header is None:
                    return
                (length,) = _LEN.unpack(header)
                if length == 0 or length > _MAX_FRAME:
                    return  # corrupt stream: drop the connection
                payload = self._read_exact(conn, length)
                if payload is None:
                    return
                self._deliver(payload)
        finally:
            with self._lock:
                self._accepted.discard(conn)
                self._read_threads.discard(threading.current_thread())
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def clock_offsets(self) -> dict[int, int]:
        """Peer id -> estimated (local - peer) monotonic offset in ns,
        learned from clock-sync hellos.  Feed to
        ``Tracer.set_clock_sync`` so obsv/merge.py can align this node's
        trace with its peers'."""
        with self._lock:
            return dict(self._clock_offsets)

    def _mac_reject(self, kind: str) -> None:
        with self._lock:
            self.mac_rejections[kind] = self.mac_rejections.get(kind, 0) + 1
        if hooks.enabled:
            hooks.metrics.counter(
                "mirbft_mac_rejections_total", kind=kind
            ).inc()

    def _open_sealed(self, payload: bytes, source: int, offset: int):
        """MAC ingress check: verify + strip the per-link tag of a
        replica-plane frame (msgfilter.check_frame_mac).  Returns the
        verified payload, or None after counting the rejection."""
        if source in (_HELLO_SRC, _XFER_SRC):
            # Reserved lanes carry the sender id as the next varint; the
            # claimed id selects the link key, and a forged claim fails
            # the tag check like any other tamper.
            peer, _ = wire.decode_varint(payload, offset)
        else:
            peer = source
        from .msgfilter import check_frame_mac

        body, kind = check_frame_mac(self.link_auth, peer, payload)
        if body is None:
            self._mac_reject(kind)
            return None
        return body

    def _deliver(self, payload: bytes) -> None:
        if self._closed.is_set():
            return  # closed transport must never deliver
        try:
            source, offset = wire.decode_varint(payload, 0)
            if self.link_auth is not None and source != _PROPOSE_SRC:
                # Replica-plane frames must carry a valid link MAC; the
                # client propose lane is signature-authenticated instead.
                payload = self._open_sealed(payload, source, offset)
                if payload is None:
                    return
            if source == _HELLO_SRC:
                peer_id, offset = wire.decode_varint(payload, offset)
                remote_ns, _ = wire.decode_varint(payload, offset)
                with self._lock:
                    self._clock_offsets[peer_id] = (
                        time.perf_counter_ns() - remote_ns
                    )
                return
            if source == _XFER_SRC:
                sender_id, offset = wire.decode_varint(payload, offset)
                sink = self._transfer_sink
                if sink is not None:
                    sink(sender_id, payload[offset:])
                return
            if source == _PROPOSE_SRC:
                _client_ep, offset = wire.decode_varint(payload, offset)
                request = pb.decode(pb.Request, payload[offset:])
            else:
                msg = pb.decode(pb.Msg, payload[offset:])
        except ValueError:
            return  # malformed frame from a faulty peer: dropped
        from .node import NodeStopped

        if source == _PROPOSE_SRC:
            # Client-lane delivery: the speculative ingress stage (see
            # set_propose_sink / runtime/ingress.py) takes precedence
            # over the direct node.propose path.
            sink = self._propose_sink
            node = self._node
            try:
                if sink is not None:
                    sink(request)
                elif node is not None:
                    node.propose(request)
            except (ValueError, NodeStopped):
                pass
            return
        node = self._node
        if node is None:
            return  # not serving yet: dropped
        try:
            node.step(source, msg)
        except (ValueError, NodeStopped):
            return  # failed preflight validation / local shutdown: dropped

    # -- shutdown --------------------------------------------------------------

    def close(self, drain_timeout: float = 0.0) -> None:
        """Tear down the transport.  With ``drain_timeout > 0`` the sender
        threads first flush queued frames over connections that are already
        established (no new dials once closing)."""
        self._closed.set()
        # shutdown() wakes the accept thread's blocked accept() NOW.  With
        # close() alone the blocked syscall pins the file description, so
        # the kernel keeps the socket in LISTEN: a "closed" transport kept
        # completing handshakes (and peers' reconnects black-holed into
        # immediately-discarded connections) until the next accept wake.
        try:
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._server.close()
        with self._lock:
            channels = list(self._channels.values())
        for channel in channels:
            channel.close(drain_timeout)
        for channel in channels:
            channel.thread.join(timeout=max(drain_timeout, 0) + 5)
        with self._lock:
            conns = [conn for conn, _lock in self._conns.values()]
            self._conns.clear()
            accepted = list(self._accepted)
            self._accepted.clear()
        for conn in conns:
            # Half-close first: the peer's reader sees a clean EOF for any
            # frames already in flight instead of a reset.
            try:
                conn.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            conn.close()
        for conn in accepted:
            # shutdown unblocks the read thread's recv immediately; close
            # alone would leave it blocked and the port ESTABLISHED.
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        # Join the accept/read threads so close() returning means no
        # transport thread is still running (no leaks across restarts).
        self._accept_thread.join(timeout=5)
        with self._lock:
            readers = list(self._read_threads)
        current = threading.current_thread()
        for thread in readers:
            if thread is not current:
                thread.join(timeout=5)
