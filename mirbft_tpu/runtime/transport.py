"""TCP transport: the Link contract over real sockets (the DCN path).

The reference deliberately ships no transport — the entire contract is
``Link.Send(dest, msg)``, fire-and-forget and unreliable-by-assumption
(reference: processor.go:23-25); the protocol tolerates loss via
retransmit ticks.  This module is the consumer-side implementation for
multi-host deployments: length-prefixed frames of the deterministic wire
codec over persistent TCP connections between replica hosts, with the
same drop-on-failure semantics the protocol already assumes.

Authentication note: the reference makes source authentication the
caller's job (mirbft.go:297-301).  Frames carry a claimed source id; a
production deployment wraps the sockets in mutually-authenticated TLS and
checks the claim against the peer certificate.  In-process and test use
trust the header, exactly like the reference's test transports.

Frame format: [u32 little-endian total length][varint source][pb.Msg].
"""

from __future__ import annotations

import socket
import struct
import threading

from .. import pb, wire
from .processor import Link

_LEN = struct.Struct("<I")
_MAX_FRAME = 64 * 1024 * 1024


class TcpTransport:
    """One replica's endpoint: a listening socket delivering inbound
    messages to the local Node, and lazily-connected outbound links."""

    def __init__(self, node_id: int, host: str = "127.0.0.1", port: int = 0):
        self.node_id = node_id
        self._node = None
        self._peers: dict[int, tuple] = {}  # id -> (host, port)
        # id -> (socket, per-connection send lock).  The transport-wide
        # _lock guards only the maps; sends serialize per peer so one
        # stalled peer cannot block broadcast to the others.
        self._conns: dict[int, tuple[socket.socket, threading.Lock]] = {}
        # Accepted inbound sockets.  close() must shutdown+close these too:
        # leaving them open keeps their read threads blocked in recv, keeps
        # the port occupied past a rebind, and — worse — lets a "closed"
        # transport keep delivering frames to its sink.
        self._accepted: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._closed = threading.Event()

        self._server = socket.create_server((host, port))
        self.address = self._server.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"tcp-accept-{node_id}",
            daemon=True,
        )
        self._accept_thread.start()

    # -- wiring ----------------------------------------------------------------

    def serve(self, node) -> None:
        """Attach the local Node; inbound frames become node.step calls."""
        self._node = node

    def connect(self, peer_id: int, address: tuple) -> None:
        """Register a peer's address; connections are opened lazily on the
        first send and re-opened after failures."""
        with self._lock:
            self._peers[peer_id] = tuple(address)

    # -- outbound --------------------------------------------------------------

    def link(self) -> Link:
        transport = self

        class _TcpLink(Link):
            def send(self, dest: int, msg: pb.Msg) -> None:
                transport._send(dest, msg)

        return _TcpLink()

    def _send(self, dest: int, msg: pb.Msg) -> None:
        payload = wire.encode_varint(self.node_id) + pb.encode(msg)
        frame = _LEN.pack(len(payload)) + payload
        with self._lock:
            entry = self._conns.get(dest)
            address = self._peers.get(dest)
        if entry is None:
            if address is None or self._closed.is_set():
                return  # unknown peer: dropped, like any unreachable host
            try:
                conn = socket.create_connection(address, timeout=5)
            except OSError:
                return  # peer down: dropped; retransmit ticks recover
            entry = (conn, threading.Lock())
            with self._lock:
                # Re-check under the lock: close() may have swept _conns
                # while create_connection blocked; inserting now would leak
                # the socket past shutdown.
                if self._closed.is_set():
                    conn.close()
                    return
                existing = self._conns.setdefault(dest, entry)
            if existing is not entry:
                conn.close()
                entry = existing
        conn, send_lock = entry
        try:
            with send_lock:
                conn.sendall(frame)
        except OSError:
            with self._lock:
                if self._conns.get(dest) is entry:
                    del self._conns[dest]
            conn.close()

    # -- inbound ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # closed
            with self._lock:
                if self._closed.is_set():
                    conn.close()
                    return
                self._accepted.add(conn)
            threading.Thread(
                target=self._read_loop,
                args=(conn,),
                name=f"tcp-read-{self.node_id}",
                daemon=True,
            ).start()

    def _read_loop(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                header = self._read_exact(conn, _LEN.size)
                if header is None:
                    return
                (length,) = _LEN.unpack(header)
                if length == 0 or length > _MAX_FRAME:
                    return  # corrupt stream: drop the connection
                payload = self._read_exact(conn, length)
                if payload is None:
                    return
                self._deliver(payload)
        finally:
            with self._lock:
                self._accepted.discard(conn)
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _deliver(self, payload: bytes) -> None:
        if self._closed.is_set():
            return  # closed transport must never deliver
        node = self._node
        if node is None:
            return  # not serving yet: dropped
        try:
            source, offset = wire.decode_varint(payload, 0)
            msg = pb.decode(pb.Msg, payload[offset:])
        except ValueError:
            return  # malformed frame from a faulty peer: dropped
        from .node import NodeStopped

        try:
            node.step(source, msg)
        except (ValueError, NodeStopped):
            return  # failed preflight validation / local shutdown: dropped

    # -- shutdown --------------------------------------------------------------

    def close(self) -> None:
        self._closed.set()
        self._server.close()
        with self._lock:
            conns = [conn for conn, _lock in self._conns.values()]
            self._conns.clear()
            accepted = list(self._accepted)
            self._accepted.clear()
        for conn in conns:
            conn.close()
        for conn in accepted:
            # shutdown unblocks the read thread's recv immediately; close
            # alone would leave it blocked and the port ESTABLISHED.
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
