"""The runtime around the deterministic core: threads, IO, the TPU executor.

This is the rebuild of the reference's L2-L4 (Node facade + serializer
goroutine + processors, reference: mirbft.go, serializer.go, processor.go)
and L3 storage (simplewal/, reqstore/).  The protocol core stays
single-threaded behind the serializer; executors carry out Actions under
the safety contract (requests + WAL durable before sends; hashing
order-free; commits independent), with the TPU processor batching all hash
work per actions-batch into one kernel launch.
"""

from .config import Config  # noqa: F401
from .log import ConsoleLogger, LogLevel  # noqa: F401
from .node import ClientProposer, Node  # noqa: F401
from .processor import (  # noqa: F401
    PipelinedProcessor,
    PoolProcessor,
    ProcessorClosed,
    SerialProcessor,
    TpuPipelinedProcessor,
    TpuPoolProcessor,
    TpuProcessor,
    build_processor,
)
from .storage import FileRequestStore, FileWal  # noqa: F401
from .transport import TcpTransport  # noqa: F401
