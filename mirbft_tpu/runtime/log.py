"""Leveled key-value logging (reference: logger.go:13-62)."""

from __future__ import annotations

import enum
import sys


class LogLevel(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    WARN = 2
    ERROR = 3


class Logger:
    """Minimal interface: level methods taking a message + kv pairs."""

    def log(self, level: LogLevel, text: str, **kv) -> None:
        raise NotImplementedError

    def debug(self, text: str, **kv) -> None:
        self.log(LogLevel.DEBUG, text, **kv)

    def info(self, text: str, **kv) -> None:
        self.log(LogLevel.INFO, text, **kv)

    def warn(self, text: str, **kv) -> None:
        self.log(LogLevel.WARN, text, **kv)

    def error(self, text: str, **kv) -> None:
        self.log(LogLevel.ERROR, text, **kv)


class ConsoleLogger(Logger):
    def __init__(self, min_level: LogLevel = LogLevel.WARN, stream=None):
        self.min_level = min_level
        self.stream = stream if stream is not None else sys.stderr

    def log(self, level: LogLevel, text: str, **kv) -> None:
        if level < self.min_level:
            return
        pairs = " ".join(f"{k}={v!r}" for k, v in kv.items())
        print(f"[{level.name}] {text}" + (f" {pairs}" if pairs else ""),
              file=self.stream)
