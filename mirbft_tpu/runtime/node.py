"""The Node facade and the serializer thread.

Rebuild of the reference's public API + serializer (reference:
mirbft.go:44-459, serializer.go:25-257).  All inputs — steps from transport
threads, proposals from client threads, ticks, action results — funnel
through one queue into the single protocol thread, which owns the
StateMachine exclusively.  Accumulated Actions are handed to the consumer
through a one-slot outbox; each handoff is marked with an ActionsReceived
event so recorded logs tie results to the actions that caused them.
"""

from __future__ import annotations

import queue
import threading

from .. import pb
from ..core.state_machine import StateMachine
from ..obsv import hooks
from .config import Config
from .msgfilter import MalformedMessage, pre_process


class NodeStopped(Exception):
    pass


class _BootstrapWal:
    """Synthesizes the initial CEntry + FEntry for a fresh network
    (reference: mirbft.go:162-190).  The serializer re-persists these into
    the real WAL so subsequent starts use restart_node."""

    def __init__(
        self,
        initial_network_state,
        initial_checkpoint_value,
        initial_leaders=None,
    ):
        self.initial_network_state = initial_network_state
        self.initial_checkpoint_value = initial_checkpoint_value
        # Epoch-0 leader set; defaults to every node.  A cluster that
        # provisions not-yet-started members (join_node) boots with the
        # running subset as leaders so the absent member's buckets don't
        # stall the network until the first suspicion round.
        self.initial_leaders = initial_leaders

    def load_all(self, for_each):
        for_each(
            1,
            pb.Persistent(
                type=pb.CEntry(
                    seq_no=0,
                    checkpoint_value=self.initial_checkpoint_value,
                    network_state=self.initial_network_state,
                )
            ),
        )
        for_each(
            2,
            pb.Persistent(
                type=pb.FEntry(
                    ends_epoch_config=pb.EpochConfig(
                        number=0,
                        leaders=(
                            self.initial_leaders
                            if self.initial_leaders is not None
                            else self.initial_network_state.config.nodes
                        ),
                    )
                )
            ),
        )


class _EmptyReqStore:
    def uncommitted(self, for_each):
        pass


def standard_initial_network_state(
    node_count: int,
    client_ids,
    *,
    nodes=None,
    checkpoint_interval: int | None = None,
    max_epoch_length: int | None = None,
) -> pb.NetworkState:
    """Default protocol constants (reference: mirbft.go:125-154).

    The keyword overrides exist so embedders can *construct* a
    non-default genesis (scenario checkpoint intervals, a
    reconfiguration joiner's target node set) instead of mutating the
    returned config in place — in-place NetworkConfig mutation outside
    the adoption seam is banned by lint rule W20."""
    members = list(nodes) if nodes is not None else list(range(node_count))
    buckets = len(members)
    ci = (
        int(checkpoint_interval)
        if checkpoint_interval
        else 5 * buckets
    )
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=members,
            f=(len(members) - 1) // 3,
            number_of_buckets=buckets,
            checkpoint_interval=ci,
            max_epoch_length=(
                int(max_epoch_length) if max_epoch_length else 10 * ci
            ),
        ),
        clients=[
            pb.NetworkClient(id=cid, width=100, low_watermark=0)
            for cid in client_ids
        ],
    )


class _Waiter:
    """Runtime mirror of the core's ClientWaiter: a real event to block on."""

    def __init__(self, core_waiter):
        self.core = core_waiter
        self.expired = threading.Event()


class Node:
    """Thread-safe facade over the serializer thread."""

    def __init__(self, config: Config, wal_storage, req_storage):
        self.config = config
        self._inbox: queue.Queue = queue.Queue()
        self._outbox: queue.Queue = queue.Queue(maxsize=1)
        self._stopped = threading.Event()
        self._stop_lock = threading.Lock()
        self._stop_done = False
        self._exit_error: BaseException | None = None
        self._machine = StateMachine(
            logger=config.logger, ack_plane=config.ack_plane,
            ack_flush_rows=config.ack_flush_rows,
        )
        if config.shadow_stride is not None and hooks.enabled and (
            hooks.shadow is None
        ):
            # Config-driven divergence oracle: audit every Nth ack frame
            # (host mirror or device plane) without the embedder having
            # to install a sampler by hand.
            from ..obsv.shadow import ShadowSampler

            hooks.shadow = ShadowSampler(
                stride=config.shadow_stride,
                registry=hooks.metrics,
                recorder=hooks.recorder,
            )
        self._waiters: list[_Waiter] = []
        self._wal_storage = wal_storage
        self._req_storage = req_storage
        self.app_stream = None  # set by attach_app
        self._exporter = None
        if config.metrics_port is not None:
            from ..obsv.exporter import ObsvExporter

            self._exporter = ObsvExporter(
                host=config.metrics_host,
                port=config.metrics_port,
                registry_fn=self._live_registry,
                status_fn=self._status_json,
                node_id=config.id,
                dump_fn=self._flight_dump,
            )
        self._thread = threading.Thread(
            target=self._run, name=f"mirbft-serializer-{config.id}", daemon=True
        )
        self._thread.start()

    # -- construction helpers ------------------------------------------------

    @classmethod
    def start_new(
        cls,
        config: Config,
        initial_network_state: pb.NetworkState,
        initial_checkpoint_value: bytes = b"",
        initial_leaders=None,
    ) -> "Node":
        return cls(
            config,
            _BootstrapWal(
                initial_network_state,
                initial_checkpoint_value,
                initial_leaders=initial_leaders,
            ),
            _EmptyReqStore(),
        )

    @classmethod
    def restart(cls, config: Config, wal_storage, req_storage) -> "Node":
        return cls(config, wal_storage, req_storage)

    # -- public API (thread-safe) --------------------------------------------

    def attach_app(self, app, *, state_path=None, queue_depth=256,
                   data_source=None):
        """Register a replicated state machine and return the commit
        stream: an ordered, exactly-once-per-apply-index delivery of
        committed ops into ``app.apply(client_id, req_no, seq_no,
        apply_index, data)``, with the applied index persisted inside the
        app snapshot at ``state_path`` so restart and snapshot install
        resume without re-applying.  The returned ``CommitStream`` is the
        ``Log`` to hand to ``build_processor`` (or to compose with a
        durable journal via ``app.AppLog``); ``app_status()`` reads its
        frontier.  See docs/APP.md."""
        from ..app.stream import CommitStream

        self.app_stream = CommitStream(
            app,
            node_id=self.config.id,
            state_path=state_path,
            queue_depth=queue_depth,
            data_source=data_source,
        )
        return self.app_stream

    def app_status(self) -> dict | None:
        """The attached commit stream's frontier/queue status (None when
        no app is attached)."""
        return None if self.app_stream is None else self.app_stream.status()

    def step(self, source: int, msg: pb.Msg) -> None:
        """Inbound authenticated message from the transport.  Structural
        and size-bound validation runs in the caller's thread; rejections
        are counted by taxonomy kind before the exception propagates (the
        transport drops the frame)."""
        try:
            pre_process(msg, self.config)
        except MalformedMessage as err:
            if hooks.enabled:
                hooks.metrics.counter(
                    "mirbft_byzantine_rejections_total", kind=err.kind
                ).inc()
            raise
        self._put(("step", source, msg))

    def propose(self, request: pb.Request) -> None:
        self._put(("propose", request))

    def tick(self) -> None:
        self._put(("tick",))

    def add_results(self, results) -> None:
        """results: core.actions.ActionResults"""
        self._put(("results", results))

    def state_transfer_complete(self, target, network_state) -> None:
        self._put(
            (
                "transfer",
                pb.CEntry(
                    seq_no=target.seq_no,
                    checkpoint_value=target.value,
                    network_state=network_state,
                ),
            )
        )

    def state_transfer_failed(self, target) -> None:
        self._put(
            (
                "transfer",
                pb.CEntry(
                    seq_no=target.seq_no,
                    checkpoint_value=target.value,
                    network_state=None,
                ),
            )
        )

    def ready(self, timeout: float | None = None):
        """Block for the next batch of Actions; None on timeout/stop."""
        try:
            actions = self._outbox.get(timeout=timeout)
        except queue.Empty:
            return None
        # Wake the serializer: actions accumulated while the one-slot outbox
        # was full should be handed off now, not when the next inbound event
        # (often a whole tick later) arrives.
        self._inbox.put(("wake",))
        return actions

    def client_proposer(self, client_id: int, blocking: bool = True):
        waiter = self._request_waiter(client_id)
        if waiter is None:
            raise ValueError(f"client {client_id} not registered")
        return ClientProposer(self, client_id, waiter, blocking)

    def status(self, timeout: float = 5.0):
        reply: queue.Queue = queue.Queue(maxsize=1)
        self._put(("status", reply))
        try:
            return reply.get(timeout=timeout)
        except queue.Empty:
            return None

    def audit_divergence(self, timeout: float = 5.0):
        """Run the scalar/vector divergence oracle (obsv.shadow) over this
        node's client tracker, on the serializer thread (the tracker is
        never safe to touch from outside it).  Returns the divergence list,
        or None when the node is stopped or the audit timed out."""
        reply: queue.Queue = queue.Queue(maxsize=1)
        try:
            self._put(("shadow_audit", reply))
            return reply.get(timeout=timeout)
        except (NodeStopped, queue.Empty):
            return None

    def stop(self) -> None:
        """Idempotent, concurrency-safe shutdown: the first caller tears
        down (serializer joined, exporter closed); later and concurrent
        callers wait for that teardown rather than racing it."""
        with self._stop_lock:
            if not self._stop_done:
                self._stop_done = True
                self._stopped.set()
                # Bypass _put: it refuses new work once stopped, but the
                # sentinel must always reach the serializer.
                self._inbox.put(("stop",))
            self._thread.join(timeout=10)
            self._close_exporter()
            if self.app_stream is not None:
                self.app_stream.close()

    @property
    def exit_error(self):
        return self._exit_error

    @property
    def retired(self) -> bool:
        """True once an adopted reconfiguration excluded this node from
        the active member set — the embedder should drain and exit.
        Plain cross-thread read of a bool the serializer only ever flips
        False→True; monitoring-grade, no lock needed."""
        return self._machine.retired

    def reconfig_status(self) -> dict:
        """Monitoring-grade reconfiguration counters (adopted count,
        retirement, pending backlog).  Reads serializer-owned state
        without synchronization: single attribute loads of values the
        serializer replaces atomically, for status files and dashboards
        only — never for protocol decisions."""
        machine = self._machine
        pending = 0
        commit_state = machine.commit_state
        if commit_state is not None and commit_state.active_state is not None:
            pending = len(commit_state.active_state.pending_reconfigurations)
        return {
            "adopted": machine.reconfigs_adopted,
            "retired": machine.retired,
            "pending": pending,
        }

    @property
    def metrics_address(self):
        """``(host, port)`` of the HTTP endpoint, or None when disabled."""
        return self._exporter.address if self._exporter is not None else None

    def set_ready(self, ready: bool) -> None:
        """Flip the /healthz readiness flag (no-op without an exporter).
        The cluster worker reports not-ready between boot and transport
        wiring so the supervisor's handshake observes a true mesh."""
        if self._exporter is not None:
            self._exporter.ready = ready

    # -- HTTP endpoint plumbing (runs on exporter request threads) -----------

    def _live_registry(self):
        return hooks.metrics if hooks.enabled else None

    def _status_json(self):
        if self._stopped.is_set():
            return None
        try:
            status = self.status(timeout=2.0)
        except NodeStopped:
            return None
        return status.to_json() if status is not None else None

    def _flight_dump(self, reason="endpoint"):
        """Flush the wired flight recorder; None when none is wired
        (the exporter maps that to 503)."""
        recorder = hooks.recorder if hooks.enabled else None
        if recorder is None:
            return None
        return recorder.flush(reason)

    def _close_exporter(self):
        if self._exporter is not None:
            self._exporter.close()

    def _put(self, item) -> None:
        if self._stopped.is_set() and item[0] != "stop":
            raise NodeStopped(str(self._exit_error or "stopped"))
        self._inbox.put(item)

    def _request_waiter(self, client_id: int):
        reply: queue.Queue = queue.Queue(maxsize=1)
        self._put(("waiter", client_id, reply))
        return reply.get(timeout=5)

    # -- the serializer thread -----------------------------------------------

    def _apply(self, event: pb.StateEvent, actions) -> None:
        if self.config.event_interceptor is not None:
            self.config.event_interceptor(event)
        if hooks.enabled and hooks.recorder is not None:
            hooks.recorder.record_event(
                type(event.type).__name__, node=self.config.id
            )
        actions.concat(self._machine.apply_event(event))

    def _run(self) -> None:
        from ..core.actions import Actions

        actions = Actions()
        try:
            self._apply(
                pb.StateEvent(
                    type=pb.EventInitialize(
                        initial_parms=pb.InitialParameters(
                            id=self.config.id,
                            batch_size=self.config.batch_size,
                            heartbeat_ticks=self.config.heartbeat_ticks,
                            suspect_ticks=self.config.suspect_ticks,
                            new_epoch_timeout_ticks=self.config.new_epoch_timeout_ticks,
                            buffer_size=self.config.buffer_size,
                        )
                    )
                ),
                actions,
            )

            is_bootstrap = isinstance(self._wal_storage, _BootstrapWal)
            loaded = 0

            def load_entry(index, entry):
                nonlocal loaded
                loaded += 1
                if is_bootstrap:
                    # Re-persist the synthesized log into the real WAL.
                    actions.persist(index, entry)
                self._apply(
                    pb.StateEvent(
                        type=pb.EventLoadEntry(index=index, data=entry)
                    ),
                    actions,
                )

            self._wal_storage.load_all(load_entry)
            if not is_bootstrap and loaded == 0:
                # Restart-from-disk hardening: an empty WAL on restart
                # means the log was lost or the wrong directory was
                # mounted.  Silently proceeding would re-initialize at
                # seq 0 and fork against the rest of the cluster; fail
                # loudly instead (surfaced via exit_error).
                raise RuntimeError(
                    "restart with empty WAL: refusing to rejoin without "
                    "a persisted checkpoint (use start_new to bootstrap)"
                )

            def load_request(ack):
                # Discard resulting actions: replayed request acks must not
                # re-store or re-broadcast immediately (the retransmit tick
                # handles re-acking, reference: serializer.go:170-186).
                self._apply(
                    pb.StateEvent(type=pb.EventLoadRequest(request_ack=ack)),
                    Actions(),
                )

            self._req_storage.uncommitted(load_request)

            self._apply(
                pb.StateEvent(type=pb.EventCompleteInitialization()), actions
            )

            while True:
                self._flush_outbox(actions)
                self._notify_waiters()
                item = self._inbox.get()
                kind = item[0]
                if kind == "stop":
                    return
                if kind == "wake":
                    continue  # flush retried at the top of the loop
                if kind == "step":
                    self._apply(
                        pb.StateEvent(
                            type=pb.EventStep(source=item[1], msg=item[2])
                        ),
                        actions,
                    )
                elif kind == "propose":
                    self._apply(
                        pb.StateEvent(type=pb.EventPropose(request=item[1])),
                        actions,
                    )
                elif kind == "tick":
                    self._apply(pb.StateEvent(type=pb.EventTick()), actions)
                elif kind == "results":
                    from ..core.actions import results_to_event

                    self._apply(
                        pb.StateEvent(type=results_to_event(item[1])), actions
                    )
                elif kind == "transfer":
                    self._apply(
                        pb.StateEvent(type=pb.EventTransfer(c_entry=item[1])),
                        actions,
                    )
                elif kind == "waiter":
                    client = self._machine.client_tracker.client(item[1])
                    if client is None:
                        item[2].put(None)
                    else:
                        waiter = _Waiter(client.client_waiter)
                        self._waiters.append(waiter)
                        item[2].put(waiter)
                elif kind == "status":
                    from ..status import state_machine_status

                    item[1].put(state_machine_status(self._machine))
                elif kind == "shadow_audit":
                    from ..obsv import shadow

                    # An oracle bug must not crash a consensus node: report
                    # it as a divergence record instead (callers fail the
                    # audit loudly without losing the serializer).
                    try:
                        divs = shadow.audit_tracker(
                            self._machine.client_tracker
                        )
                    except Exception as audit_err:
                        divs = [
                            {
                                "component": "audit_error",
                                "slot": -1,
                                "client_id": -1,
                                "req_no": -1,
                                "detail": repr(audit_err),
                            }
                        ]
                    item[1].put(divs)
                else:
                    raise AssertionError(f"unknown inbox item {kind!r}")
        except BaseException as err:  # noqa: BLE001 — surfaced via exit_error
            self._exit_error = err
            self.config.logger.error(
                "serializer thread exiting", error=repr(err)
            )
            # The black box outlives the crash: note the error and flush
            # so the postmortem timeline ends at the failure.
            try:
                if hooks.enabled and hooks.recorder is not None:
                    hooks.recorder.record_note(
                        "serializer.crash",
                        node=self.config.id,
                        args={"error": repr(err)},
                    )
                    hooks.recorder.flush("serializer-crash")
            except Exception:
                pass  # dumping is best-effort on the crash path
        finally:
            self._stopped.set()
            for waiter in self._waiters:
                waiter.expired.set()
            # Serializer death (clean stop or crash — chaos crash
            # schedules included) takes the scrape surface down with it.
            self._close_exporter()

    def _flush_outbox(self, actions) -> None:
        from ..core.actions import Actions

        if actions.is_empty() or self._outbox.full():
            return
        handoff = Actions().concat(actions)
        actions.clear()
        try:
            self._outbox.put_nowait(handoff)
        except queue.Full:
            actions.concat(handoff)
            return
        self._apply(pb.StateEvent(type=pb.EventActionsReceived()), actions)

    def _notify_waiters(self) -> None:
        live = []
        for waiter in self._waiters:
            # The core flips .expired when the window moves; mirror it onto
            # the runtime event and refresh the registration.
            if waiter.core.expired:
                waiter.expired.set()
            else:
                live.append(waiter)
        self._waiters = live


class ClientProposer:
    """Watermark-backpressured proposal API for one client (reference:
    mirbft.go:53-122)."""

    def __init__(self, node: Node, client_id: int, waiter, blocking: bool):
        self.node = node
        self.client_id = client_id
        self._waiter = waiter
        self.blocking = blocking

    def propose(self, request: pb.Request, timeout: float | None = 30.0) -> None:
        while True:
            low = self._waiter.core.low_watermark
            high = self._waiter.core.high_watermark
            if request.req_no < low:
                raise ValueError(
                    f"request {request.req_no} below low watermark {low}"
                )
            if request.req_no <= high:
                break
            if not self.blocking:
                raise ValueError("request above watermarks (non-blocking)")
            if not self._waiter.expired.wait(timeout=timeout):
                raise TimeoutError("window did not move in time")
            refreshed = self.node._request_waiter(self.client_id)
            if refreshed is None:
                raise NodeStopped("client no longer registered")
            self._waiter = refreshed
        self.node.propose(request)
