"""Speculative batched ingress verification for the live runtime.

The deterministic engine's speculative plane
(`testengine/signing.py:SpeculativeSignaturePlane`) parks submissions
until the simulated wave boundary; the live runtime has no simulated
clock, so the same idea runs as a pipelined verify stage — the ticket
pattern of `runtime/processor.py`, one stage deep: client requests are
admitted optimistically into a bounded pre-consensus queue and a worker
thread drains the queue in batches, calling an injected batch verifier
and delivering only the survivors to the node's propose path.

Verification therefore overlaps consensus instead of gating intake: the
socket read thread never blocks on curve arithmetic, the batch amortizes
the per-signature cost (RLC on the host, pow2-bucketed kernel rows on a
device — the caller injects whichever authority applies, see
docs/CRYPTO.md), and a request whose signature fails is evicted before
it can reach the ordered log.

W21 discipline: this module holds **no** crypto.  ``verify_batch_fn``
([(client_id, req_no, data)] -> [bool]) is injected by the embedder
(chaos/live.py and cluster/worker.py inject `testengine.signing`'s
verifiers); runtime/ never touches key material or verify primitives.
"""

from __future__ import annotations

import threading
import time

from ..obsv import hooks


class SpeculativeIngress:
    """One node's speculative client-request verify stage.

    ``submit(request)`` parks the request (optimistic admission) and
    returns immediately; the worker verifies parked requests in batches
    of up to ``max_batch`` and hands survivors to ``deliver`` (typically
    ``node.propose``).  ``deliver`` runs on the worker thread and must
    not block indefinitely.
    """

    def __init__(
        self,
        deliver,
        verify_batch_fn,
        max_batch: int = 256,
        queue_depth: int = 8192,
        name: str = "ingress",
    ):
        self.deliver = deliver
        self.verify_batch_fn = verify_batch_fn
        self.max_batch = max_batch
        self.queue_depth = queue_depth
        self.admitted = 0
        self.delivered = 0
        self.evicted = 0
        self.dropped_overflow = 0
        self.batches = 0
        self.flush_sizes: list[int] = []
        self.flush_wall_s: list[float] = []
        self._queue: list = []
        self._cv = threading.Condition()
        self._outstanding = 0  # parked + in the batch being verified
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"spec-{name}", daemon=True
        )
        self._thread.start()

    # -- admission (any thread) --------------------------------------------

    def submit(self, request) -> bool:
        """Optimistically admit one client request; False if the stage is
        saturated or closed (the request is dropped — client retry is the
        recovery path, exactly like a transport overflow)."""
        with self._cv:
            if self._closed or len(self._queue) >= self.queue_depth:
                self.dropped_overflow += 1
                return False
            self._queue.append(request)
            self._outstanding += 1
            self.admitted += 1
            self._cv.notify()
        return True

    @property
    def depth(self) -> int:
        """Requests admitted but not yet judged (status.py speculative
        queue depth)."""
        with self._cv:
            return self._outstanding

    # -- the stage ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(timeout=0.1)
                if not self._queue:
                    if self._closed:
                        return
                    continue
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
            self._verify_and_deliver(batch)
            with self._cv:
                self._outstanding -= len(batch)
                self._cv.notify_all()

    def _verify_and_deliver(self, batch: list) -> None:
        start = time.perf_counter()
        try:
            verdicts = self.verify_batch_fn(
                [(r.client_id, r.req_no, r.data) for r in batch]
            )
        except Exception:
            # A dead verifier must fail closed: nothing speculative may
            # reach the ordered log without a verdict.
            verdicts = [False] * len(batch)
        wall = time.perf_counter() - start
        self.batches += 1
        self.flush_sizes.append(len(batch))
        self.flush_wall_s.append(wall)
        evicted = 0
        for request, ok in zip(batch, verdicts):
            if ok:
                try:
                    self.deliver(request)
                    self.delivered += 1
                except Exception:
                    pass  # node stopping: dropped like any late frame
            else:
                evicted += 1
        self.evicted += evicted
        if hooks.enabled:
            hooks.record_flush("signature", "ingress", len(batch), wall)
            if evicted:
                hooks.metrics.counter(
                    "mirbft_crypto_speculative_evictions_total"
                ).inc(evicted)

    # -- drain/shutdown ------------------------------------------------------

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every admitted request has been judged (tests and
        graceful drain); False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._outstanding:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.1))
        return True

    def close(self, drain_timeout: float = 5.0) -> None:
        self.flush(timeout=drain_timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
