"""Checkpoint-anchored snapshot state transfer over the real transport.

The core's ``actions.state_transfer`` contract (commitstate.transfer_to)
says *what* to adopt — a 2f+1-certified ``(seq_no, value)`` checkpoint —
but not *how* to obtain it; until this module, harness embedders "served"
the transfer by reaching into a peer's in-memory state, which cannot work
across a real multi-process cluster.  This is the real subsystem:

- **Donor side.** Every replica keeps the last few checkpoint-anchored
  snapshots (``note_checkpoint``): the application log state, the
  network state, and the reqstore slice above the checkpoint, serialized
  into one deterministic blob.  A snapshot REQUEST streams the blob back
  as bounded, digest-chained CHUNK frames; a request for a snapshot the
  donor no longer holds (or holds under a different certificate value)
  is NACKed so the fetcher fails over immediately instead of timing out.

- **Fetcher side.** ``begin(target)`` starts (or resumes) a fetch; the
  embedder's consumer loop drives ``poll()``.  Donors are tried in a
  seeded rotation with per-chunk timeouts, jittered-backoff retry, and
  donor failover.  Chunks verify incrementally against a digest chain
  seeded from the certified ``(seq_no, value)`` — a frame corrupted in
  flight, truncated, or served for the wrong certificate breaks the
  chain and is rejected with evidence counters.  The reassembled blob
  must decode to the exact certified target (the 2f+1 checkpoint
  certificate is the adoption authority) before anything is installed.

- **Crash safety.** A verified blob is staged to disk atomically
  (storage.write_snapshot_file) *before* installation.  If the process
  dies mid-install, the core re-emits ``state_transfer`` on restart (the
  WAL holds a TEntry newer than any CEntry), the engine finds the staged
  blob for the same target, and completes locally without the network.

Wire format (docs/STATE_TRANSFER.md): frames travel under the
transport's reserved ``_XFER_SRC`` lane and are varint-framed:

    REQUEST = kind=1, seq_no, len(value), value, resume_index
    CHUNK   = kind=2, seq_no, index, total, digest[32], len(payload), payload
    NACK    = kind=3, seq_no

Chain rule: ``d_0 = sha256(domain || seq_no || len(value) || value)``,
``d_i = sha256(d_{i-1} || payload_i)``; chunk ``i`` carries ``d_{i+1}``
computed over its own payload, so the fetcher can verify each chunk on
arrival with no buffering beyond the blob itself.

Threading: ``on_frame`` runs on transport read threads and only mutates
engine state under the lock (donor-side chunk sends are enqueue-only);
``poll`` runs on the embedder's consumer thread and owns every callback
into the embedder/node, so installs never race the consensus loop.
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
import time

from .. import pb, wire
from ..obsv import hooks
from ..resilience import Backoff
from .msgfilter import MalformedMessage, check_snapshot_chunk
from .storage import (
    read_snapshot_file,
    remove_snapshot_file,
    write_snapshot_file,
)

_DOMAIN = b"mirbft-snapshot-v1"
_DIGEST_LEN = 32

_KIND_REQUEST = 1
_KIND_CHUNK = 2
_KIND_NACK = 3

# Donor-side retention: snapshots for the newest N noted checkpoints.
# Three matches the protocol's three active checkpoint windows, plus one
# of slack for a fetcher racing a window slide.
_RETAIN_SNAPSHOTS = 4


def _counter(name: str, **labels) -> None:
    if hooks.enabled:
        hooks.metrics.counter(name, **labels).inc()


class Snapshot:
    """One decoded checkpoint-anchored snapshot."""

    __slots__ = ("seq_no", "value", "network_state", "app_bytes", "requests")

    def __init__(
        self,
        seq_no: int,
        value: bytes,
        network_state: pb.NetworkState,
        app_bytes: bytes,
        requests: list[tuple[pb.RequestAck, bytes]],
    ):
        self.seq_no = seq_no
        self.value = value
        self.network_state = network_state
        self.app_bytes = app_bytes
        self.requests = requests


# -- snapshot blob codec ------------------------------------------------------


def _put_bytes(parts: list, data: bytes) -> None:
    parts.append(wire.encode_varint(len(data)))
    parts.append(data)


def _take_bytes(blob: bytes, pos: int) -> tuple[bytes, int]:
    length, pos = wire.decode_varint(blob, pos)
    end = pos + length
    if end > len(blob):
        raise ValueError("snapshot field overruns blob")
    return blob[pos:end], end


def encode_snapshot(snap: Snapshot) -> bytes:
    parts: list = [wire.encode_varint(snap.seq_no)]
    _put_bytes(parts, snap.value)
    _put_bytes(parts, pb.encode(snap.network_state))
    _put_bytes(parts, snap.app_bytes)
    parts.append(wire.encode_varint(len(snap.requests)))
    for ack, data in snap.requests:
        _put_bytes(parts, pb.encode(ack))
        _put_bytes(parts, data or b"")
    return b"".join(parts)


def decode_snapshot(blob: bytes) -> Snapshot:
    """Decode a snapshot blob; raises ValueError on any malformation."""
    seq_no, pos = wire.decode_varint(blob, 0)
    value, pos = _take_bytes(blob, pos)
    ns_bytes, pos = _take_bytes(blob, pos)
    network_state = pb.decode(pb.NetworkState, ns_bytes)
    app_bytes, pos = _take_bytes(blob, pos)
    count, pos = wire.decode_varint(blob, pos)
    requests = []
    for _ in range(count):
        ack_bytes, pos = _take_bytes(blob, pos)
        data, pos = _take_bytes(blob, pos)
        requests.append((pb.decode(pb.RequestAck, ack_bytes), data))
    if pos != len(blob):
        raise ValueError("trailing bytes after snapshot")
    return Snapshot(seq_no, value, network_state, app_bytes, requests)


# -- chunk framing ------------------------------------------------------------


def chain_seed(seq_no: int, value: bytes) -> bytes:
    """Anchor the digest chain to the certified target: a snapshot served
    for any other (seq_no, value) fails verification at the first chunk."""
    h = hashlib.sha256()
    h.update(_DOMAIN)
    h.update(wire.encode_varint(seq_no))
    h.update(wire.encode_varint(len(value)))
    h.update(value)
    return h.digest()


def chain_next(prev: bytes, payload: bytes) -> bytes:
    return hashlib.sha256(prev + payload).digest()


def split_chunks(blob: bytes, chunk_bytes: int) -> list[bytes]:
    """Slice a blob into bounded chunk payloads (always at least one, so
    an empty blob still round-trips as a single empty chunk)."""
    if chunk_bytes < 1:
        raise ValueError("chunk_bytes must be >= 1")
    if not blob:
        return [b""]
    return [
        blob[i : i + chunk_bytes] for i in range(0, len(blob), chunk_bytes)
    ]


def encode_request(seq_no: int, value: bytes, resume_index: int) -> bytes:
    parts = [
        wire.encode_varint(_KIND_REQUEST),
        wire.encode_varint(seq_no),
    ]
    _put_bytes(parts, value)
    parts.append(wire.encode_varint(resume_index))
    return b"".join(parts)


def encode_chunk(
    seq_no: int, index: int, total: int, digest: bytes, payload: bytes
) -> bytes:
    parts = [
        wire.encode_varint(_KIND_CHUNK),
        wire.encode_varint(seq_no),
        wire.encode_varint(index),
        wire.encode_varint(total),
        digest,
    ]
    _put_bytes(parts, payload)
    return b"".join(parts)


def encode_nack(seq_no: int) -> bytes:
    return wire.encode_varint(_KIND_NACK) + wire.encode_varint(seq_no)


def decode_frame(body: bytes) -> tuple:
    """Decode one transfer frame into a tagged tuple; raises ValueError
    on malformation (the caller drops the frame, like the transport does
    for undecodable pb.Msg frames)."""
    kind, pos = wire.decode_varint(body, 0)
    if kind == _KIND_REQUEST:
        seq_no, pos = wire.decode_varint(body, pos)
        value, pos = _take_bytes(body, pos)
        resume, pos = wire.decode_varint(body, pos)
        return ("request", seq_no, value, resume)
    if kind == _KIND_CHUNK:
        seq_no, pos = wire.decode_varint(body, pos)
        index, pos = wire.decode_varint(body, pos)
        total, pos = wire.decode_varint(body, pos)
        if pos + _DIGEST_LEN > len(body):
            raise ValueError("chunk frame too short for digest")
        digest = body[pos : pos + _DIGEST_LEN]
        payload, _pos = _take_bytes(body, pos + _DIGEST_LEN)
        return ("chunk", seq_no, index, total, digest, payload)
    if kind == _KIND_NACK:
        seq_no, _pos = wire.decode_varint(body, pos)
        return ("nack", seq_no)
    raise ValueError(f"unknown transfer frame kind {kind}")


# -- the engine ---------------------------------------------------------------

_COUNTER_KEYS = (
    "snapshots_noted",
    "snapshots_served",
    "snapshots_nacked",
    "snapshots_installed",
    "snapshots_resumed_staged",
    "snapshots_failed",
    "chunks_served",
    "chunks_received",
    "chunks_rejected_corrupt",
    "chunks_rejected_oversized",
    "chunks_stale",
    "request_timeouts",
    "donor_failovers",
    "retries",
)


class TransferEngine:
    """Donor and fetcher for checkpoint-anchored snapshots.

    ``duct`` abstracts the frame path: ``duct.send(dest, body)`` must be
    non-blocking fire-and-forget (TcpTransport.send_transfer, or a direct
    in-process call in tests/loadgen).  Inbound frames are fed to
    ``on_frame(sender_id, body)``.

    Embedder callbacks (all invoked from the ``poll()`` thread):

    - ``install(snapshot) -> pb.NetworkState | None``: apply the app
      state and reqstore slice; return the network state to adopt, or
      None to veto (counts as a failed verification).
    - ``complete(target, network_state)``: forward to
      ``Node.state_transfer_complete``.
    - ``failed(target)``: forward to ``Node.state_transfer_failed`` —
      the core re-emits ``state_transfer`` and the embedder calls
      ``begin`` again, so giving up here is a retry, not a dead end.
    """

    def __init__(
        self,
        node_id: int,
        duct,
        *,
        staging_dir: str,
        peers=(),
        limits=None,
        install=None,
        complete=None,
        failed=None,
        chunk_timeout_s: float = 2.0,
        attempts_per_donor: int = 2,
        donor_rounds: int = 2,
        clock=time.monotonic,
        seed: int = 0,
    ):
        self.node_id = node_id
        self.duct = duct
        self.limits = limits
        self.install = install
        self.complete = complete
        self.failed = failed
        self.chunk_timeout_s = chunk_timeout_s
        self.attempts_per_donor = attempts_per_donor
        self.donor_rounds = donor_rounds
        self.clock = clock
        # staging_dir None = memory-only embedder (loadgen): no staged
        # blob, so crash-resume degrades to a plain re-fetch.
        self.staging_path = (
            os.path.join(staging_dir, "snapshot.staged")
            if staging_dir is not None
            else None
        )
        self._rng = random.Random(seed ^ (node_id << 16))
        self._backoff = Backoff(
            base=0.05, cap=max(chunk_timeout_s, 0.05), rng=self._rng
        )

        self._lock = threading.Lock()
        self._peers = [p for p in peers if p != node_id]  # guarded-by: _lock
        # Donor cache: seq_no -> (value, blob).  guarded-by: _lock
        self._snapshots: dict[int, tuple[bytes, bytes]] = {}
        # Fetcher state.  guarded-by: _lock
        self._phase = "idle"  # idle | init | fetching | waiting | ready
        self._target = None  # StateTarget-like (seq_no, value)
        self._donors: list[int] = []
        self._donor_idx = 0
        self._attempts = 0
        self._rounds = 0
        self._chunks: list[bytes] = []
        self._chain = b""
        self._total: int | None = None
        self._deadline = 0.0
        self._wait_until = 0.0
        # Outgoing fetch requests queued under the lock, sent after it is
        # released (_flush_outgoing).  Sending through the duct while
        # holding the lock self-deadlocks under a synchronous duct (the
        # loadgen's in-process duct delivers the donor's chunk response
        # re-entrantly on the same thread, which re-enters _on_chunk and
        # blocks on the non-reentrant lock) — and even over sockets a
        # blocking send would stall every other engine entry point.
        self._outgoing: list[tuple[int, bytes]] = []  # guarded-by: _lock
        self.counters = {key: 0 for key in _COUNTER_KEYS}

    # -- donor side ----------------------------------------------------------

    def note_checkpoint(
        self,
        seq_no: int,
        value: bytes,
        network_state: pb.NetworkState,
        app_bytes: bytes,
        requests,
    ) -> None:
        """Record a locally stable checkpoint as a servable snapshot.
        Called by the embedder when it captures a CheckpointResult; keeps
        the newest ``_RETAIN_SNAPSHOTS`` anchors."""
        blob = encode_snapshot(
            Snapshot(seq_no, value, network_state, app_bytes, list(requests))
        )
        with self._lock:
            self._snapshots[seq_no] = (value, blob)
            for old in sorted(self._snapshots)[:-_RETAIN_SNAPSHOTS]:
                del self._snapshots[old]
            self.counters["snapshots_noted"] += 1

    def set_peers(self, peers) -> None:
        """Replace the donor candidate set (a joining cluster learns new
        members after boot).  Takes effect on the next fetch round."""
        with self._lock:
            self._peers = [p for p in peers if p != self.node_id]

    def _serve(self, seq_no: int, value: bytes, resume: int):
        """Build the response frames for a REQUEST (lock held); returns
        ``(frames, served)`` — the bodies to send after the lock is
        released, and whether this was a serve (vs a NACK)."""
        entry = self._snapshots.get(seq_no)
        if entry is None or entry[0] != value:
            self.counters["snapshots_nacked"] += 1
            return [encode_nack(seq_no)], False
        _value, blob = entry
        chunk_bytes = getattr(self.limits, "max_snapshot_chunk_bytes", 256 * 1024)
        payloads = split_chunks(blob, chunk_bytes)
        total = len(payloads)
        if resume >= total:
            resume = 0  # nonsense resume point: restart the stream
        digest = chain_seed(seq_no, value)
        frames = []
        for index, payload in enumerate(payloads):
            digest = chain_next(digest, payload)
            if index >= resume:
                frames.append(
                    encode_chunk(seq_no, index, total, digest, payload)
                )
        self.counters["snapshots_served"] += 1
        self.counters["chunks_served"] += len(frames)
        return frames, True

    # -- frame ingress (transport read threads) -------------------------------

    def on_frame(self, sender: int, body: bytes) -> None:
        try:
            frame = decode_frame(body)
        except ValueError:
            with self._lock:
                self.counters["chunks_rejected_corrupt"] += 1
            _counter(
                "mirbft_transfer_chunks_total", outcome="rejected_corrupt"
            )
            return
        if frame[0] == "request":
            _tag, seq_no, value, resume = frame
            with self._lock:
                responses, served = self._serve(seq_no, value, resume)
            for response in responses:
                self.duct.send(sender, response)
            _counter(
                "mirbft_transfer_snapshots_total",
                outcome="served" if served else "nacked",
            )
            return
        if frame[0] == "chunk":
            self._on_chunk(sender, *frame[1:])
            self._flush_outgoing()
            return
        # NACK: the donor cannot serve this target — fail over now.
        _tag, seq_no = frame
        with self._lock:
            if (
                self._phase in ("fetching", "waiting")
                and self._target is not None
                and self._target.seq_no == seq_no
                and self._current_donor() == sender
            ):
                self._rotate_donor_locked()
        self._flush_outgoing()

    def _on_chunk(
        self,
        sender: int,
        seq_no: int,
        index: int,
        total: int,
        digest: bytes,
        payload: bytes,
    ) -> None:
        with self._lock:
            target = self._target
            if (
                self._phase != "fetching"
                or target is None
                or target.seq_no != seq_no
                or self._current_donor() != sender
            ):
                self.counters["chunks_stale"] += 1
                _counter("mirbft_transfer_chunks_total", outcome="stale")
                return
            try:
                check_snapshot_chunk(len(payload), total, self.limits)
            except MalformedMessage as err:
                # Byzantine donor: bounded ingress rejected the frame.
                self.counters["chunks_rejected_oversized"] += 1
                _counter(
                    "mirbft_transfer_chunks_total",
                    outcome="rejected_oversized",
                )
                _counter(
                    "mirbft_byzantine_rejections_total", kind=err.kind
                )
                self._rotate_donor_locked()
                return
            if index != len(self._chunks) or (
                self._total is not None and total != self._total
            ):
                # Duplicate or out-of-order within one TCP stream means a
                # donor restart mid-serve (its rebuilt blob may differ):
                # drop the frame; the chunk timeout re-requests.
                self.counters["chunks_stale"] += 1
                _counter("mirbft_transfer_chunks_total", outcome="stale")
                return
            expected = chain_next(self._chain, payload)
            if digest != expected:
                # Corrupted/truncated/forged in flight: reject with
                # evidence and abandon this donor's stream.
                self.counters["chunks_rejected_corrupt"] += 1
                _counter(
                    "mirbft_transfer_chunks_total", outcome="rejected_corrupt"
                )
                _counter(
                    "mirbft_byzantine_rejections_total", kind="corrupt"
                )
                self._rotate_donor_locked()
                return
            self._chain = expected
            self._chunks.append(payload)
            self._total = total
            self._deadline = self.clock() + self.chunk_timeout_s
            self.counters["chunks_received"] += 1
            _counter("mirbft_transfer_chunks_total", outcome="received")
            if len(self._chunks) == total:
                self._phase = "ready"

    # -- fetcher side ---------------------------------------------------------

    def begin(self, target) -> None:
        """Start fetching ``target`` (an object with seq_no/value).
        Idempotent while a fetch for the same target is in flight; a new
        target preempts the old fetch."""
        with self._lock:
            if (
                self._target is not None
                and self._phase != "idle"
                and self._target.seq_no == target.seq_no
                and self._target.value == target.value
            ):
                return
            self._target = target
            self._phase = "init"
            self._reset_stream_locked()
            self._donors = sorted(self._peers)
            self._rng.shuffle(self._donors)
            self._donor_idx = 0
            self._rounds = 0
            self._backoff.reset()

    def transferring(self) -> bool:
        with self._lock:
            return self._phase != "idle"

    def poll(self) -> None:
        """Advance the fetch state machine; called from the embedder's
        consumer loop (and directly by deterministic tests).  All
        embedder callbacks happen here."""
        actions = []
        with self._lock:
            now = self.clock()
            if self._phase == "init":
                actions = self._poll_init_locked()
            elif self._phase == "fetching" and now > self._deadline:
                self.counters["request_timeouts"] += 1
                self._attempts += 1
                if self._attempts < self.attempts_per_donor:
                    self.counters["retries"] += 1
                    _counter(
                        "mirbft_transfer_snapshots_total", outcome="retry"
                    )
                    self._wait_until = now + self._backoff.next()
                    self._phase = "waiting"
                else:
                    self._rotate_donor_locked()
            elif self._phase == "waiting" and now >= self._wait_until:
                self._send_request_locked(resume=len(self._chunks))
            elif self._phase == "ready":
                actions = self._poll_ready_locked()
            elif self._phase == "failed":
                actions = [self._fail_locked()]
        self._flush_outgoing()
        for action in actions:
            action()

    def _poll_init_locked(self) -> list:  # holds: _lock
        target = self._target
        blob = (
            read_snapshot_file(self.staging_path)
            if self.staging_path is not None
            else None
        )
        if blob is not None:
            snap = self._verify_blob(blob, target)
            if snap is not None:
                self.counters["snapshots_resumed_staged"] += 1
                _counter(
                    "mirbft_transfer_snapshots_total",
                    outcome="resumed_staged",
                )
                return [lambda: self._install(snap, staged=True)]
            # Staged blob is for another target (or torn semantics can't
            # happen — the write is atomic): discard and fetch fresh.
            remove_snapshot_file(self.staging_path)
        if not self._donors:
            return [self._fail_locked()]
        self._send_request_locked(resume=0)
        return []

    def _poll_ready_locked(self) -> list:  # holds: _lock
        target = self._target
        blob = b"".join(self._chunks)
        snap = self._verify_blob(blob, target)
        if snap is None:
            # Chain-valid but semantically wrong (a byzantine donor can
            # chain arbitrary bytes to the right anchor): certificate
            # verification is the final authority.
            self.counters["chunks_rejected_corrupt"] += 1
            _counter(
                "mirbft_transfer_chunks_total", outcome="rejected_corrupt"
            )
            _counter("mirbft_byzantine_rejections_total", kind="corrupt")
            self._rotate_donor_locked()
            return []
        if self.staging_path is not None:
            write_snapshot_file(self.staging_path, blob)
        return [lambda: self._install(snap, staged=False)]

    def _verify_blob(self, blob: bytes, target) -> Snapshot | None:
        """The adoption rule: the blob must decode cleanly and carry
        exactly the 2f+1-certified (seq_no, value) of the target."""
        try:
            snap = decode_snapshot(blob)
        except ValueError:
            return None
        if target is None:
            return None
        if snap.seq_no != target.seq_no or snap.value != target.value:
            return None
        if snap.network_state is None:
            return None
        return snap

    def _install(self, snap: Snapshot, staged: bool) -> None:
        """Apply a verified snapshot (poll thread, lock released)."""
        with self._lock:
            target = self._target
        network_state = (
            self.install(snap) if self.install else snap.network_state
        )
        if network_state is None:
            # Embedder veto: the blob passed certificate checks but the
            # application refused it.  A staged blob is now poisoned —
            # discard it and fetch fresh; a freshly fetched one means
            # the donor is bad — fail over.
            self._discard_staged()
            with self._lock:
                if staged:
                    if self._donors:
                        self._send_request_locked(resume=0)
                    else:
                        self._phase = "failed"
                else:
                    self._rotate_donor_locked()
            self._flush_outgoing()
            return
        with self._lock:
            self._phase = "idle"
            self.counters["snapshots_installed"] += 1
        _counter("mirbft_transfer_snapshots_total", outcome="installed")
        if self.complete is not None:
            self.complete(target, network_state)
        self._discard_staged()

    def _discard_staged(self) -> None:
        if self.staging_path is not None:
            remove_snapshot_file(self.staging_path)

    # -- fetch-state helpers (lock held) --------------------------------------

    def _current_donor(self) -> int | None:
        if not self._donors:
            return None
        return self._donors[self._donor_idx % len(self._donors)]

    def _reset_stream_locked(self) -> None:
        self._chunks = []
        self._total = None
        target = self._target
        self._chain = (
            chain_seed(target.seq_no, target.value) if target else b""
        )

    def _send_request_locked(self, resume: int) -> None:  # holds: _lock
        donor = self._current_donor()
        target = self._target
        self._phase = "fetching"
        self._deadline = self.clock() + self.chunk_timeout_s
        if resume == 0:
            self._reset_stream_locked()
        # Queue, don't send: the caller flushes after releasing the lock.
        self._outgoing.append(
            (donor, encode_request(target.seq_no, target.value, resume))
        )

    def _flush_outgoing(self) -> None:
        """Send queued fetch requests with the lock released (see the
        _outgoing comment in __init__)."""
        while True:
            with self._lock:
                if not self._outgoing:
                    return
                donor, frame = self._outgoing.pop(0)
            self.duct.send(donor, frame)

    def _rotate_donor_locked(self) -> None:  # holds: _lock
        """Abandon the current donor's stream and move to the next; after
        ``donor_rounds`` full cycles, report failure to the core (which
        re-emits state_transfer, restarting the whole fetch)."""
        self._attempts = 0
        self._backoff.reset()
        self._donor_idx += 1
        if not self._donors or self._donor_idx % len(self._donors) == 0:
            self._rounds += 1
            if not self._donors or self._rounds >= self.donor_rounds:
                # Every donor exhausted: hand the verdict to the next
                # poll() so the failure callback fires on the embedder's
                # consumer thread, like every other callback.
                self._phase = "failed"
                return
        self.counters["donor_failovers"] += 1
        _counter(
            "mirbft_transfer_snapshots_total", outcome="donor_failover"
        )
        self._send_request_locked(resume=0)

    def _fail_locked(self):
        target = self._target
        self._phase = "idle"
        self.counters["snapshots_failed"] += 1
        _counter("mirbft_transfer_snapshots_total", outcome="failed")

        def fire():
            if self.failed is not None:
                self.failed(target)

        return fire

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        """Snapshot for status.py's transfer section."""
        with self._lock:
            target = self._target
            return {
                "phase": self._phase,
                "target_seq_no": target.seq_no if target else None,
                "donor": self._current_donor()
                if self._phase in ("fetching", "waiting")
                else None,
                "chunks_received": len(self._chunks),
                "total_chunks": self._total,
                "cached_snapshots": sorted(self._snapshots),
                "counters": dict(self.counters),
            }
