"""Durable storage: the write-ahead log and the request store.

Rebuild of the reference's storage layer (reference:
simplewal/simplewal.go:22-109 over tidwall/wal; reqstore/reqstore.go:24-100
over BadgerDB) as dependency-free file formats:

- FileWal: an append-only segmented log of (index, Persistent) records.
  Appends go to the active segment; ``truncate(index)`` (truncate-front)
  deletes whole segments below the index and tombstones the rest via a
  head-index marker; ``sync`` fsyncs.  Records are length-prefixed canonical
  encodings with a CRC so torn tails are detected and discarded on load.
- FileRequestStore: an append-only intent log of store/commit records with
  an in-memory index; ``uncommitted`` replays stores minus commits at
  startup; compaction rewrites the live set on open.

Both stores expose a group-commit API on top of their synchronous
``sync()``: ``sync_token()`` registers a durability request and returns a
ticket; ``wait(token)`` blocks until an fsync issued *after* the ticket
has completed.  A single background syncer drains all outstanding tickets
with one ``os.fsync``, so k in-flight batches (the pipelined processor
keeps several) pay ~1 fsync instead of k.  The coalescing ratio is
observable as ``mirbft_*_group_commit_batches`` / ``mirbft_*_fsyncs_total``
and the honest per-waiter latency (issue-to-durable, including queueing)
as ``mirbft_*_group_sync_wait_seconds``.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

from .. import pb, wire
from ..obsv import hooks

_REC_HEADER = struct.Struct("<IQI")  # payload_len, index, crc32(payload)
_SEGMENT_TARGET = 4 * 1024 * 1024


def _fsync_dir(path: str) -> None:
    """fsync a directory: os.replace/creat/unlink order *data*, but the
    directory entry itself is not durable until the directory inode is
    synced — without this, a crash after compaction/truncation can come
    back up with the pre-rename file (or both, or neither)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; best effort
    finally:
        os.close(fd)


def _tree_bytes(path: str) -> int:
    """Sum of regular-file sizes under ``path``; races with concurrent
    rotation/compaction count a vanished file as zero."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.stat(os.path.join(root, name)).st_size
            except OSError:
                continue
    return total


class CorruptWal(Exception):
    pass


class _GroupCommit:
    """Ticketed fsync coalescer shared by FileWal and FileRequestStore.

    ``token()`` hands out monotonically increasing tickets; a lazily
    started syncer thread snapshots the highest outstanding ticket, runs
    the owner's ``sync()`` once, and marks every ticket up to the
    snapshot complete.  Waiters observe their own issue-to-durable
    latency, so the histogram stays honest about queueing delay rather
    than reporting only the fsync syscall time."""

    def __init__(self, sync_fn, name: str, batches_metric: str, wait_metric: str):
        self._sync_fn = sync_fn
        self._name = name
        self._batches_metric = batches_metric
        self._wait_metric = wait_metric
        self._cv = threading.Condition()
        self._requested = 0  # guarded-by: _cv
        self._completed = 0  # guarded-by: _cv
        self._issue_ts: dict[int, float] = {}  # guarded-by: _cv
        self._error: BaseException | None = None  # guarded-by: _cv
        self._stopping = False  # guarded-by: _cv
        self._thread: threading.Thread | None = None  # guarded-by: _cv

    def token(self) -> int:
        with self._cv:
            if self._stopping:
                raise OSError(f"{self._name}: storage closed")
            if self._error is not None:
                raise self._error
            self._requested += 1
            token = self._requested
            self._issue_ts[token] = time.perf_counter()
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._cv.notify_all()
            return token

    def wait(self, token: int, timeout: float | None = None) -> bool:
        """Block until the ticket's data is durable.  Returns False on
        timeout; raises the syncer's error (e.g. a failing disk) or
        OSError if the store was closed with the ticket uncovered."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._cv:
            while self._completed < token:
                if self._error is not None:
                    self._issue_ts.pop(token, None)
                    raise self._error
                if self._stopping:
                    self._issue_ts.pop(token, None)
                    raise OSError(f"{self._name}: closed before sync completed")
                if deadline is None:
                    self._cv.wait()
                else:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        return False
                    self._cv.wait(timeout=remaining)
            start = self._issue_ts.pop(token, None)
        if hooks.enabled and start is not None:
            hooks.metrics.histogram(self._wait_metric).observe(
                time.perf_counter() - start
            )
        return True

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._completed >= self._requested and not self._stopping:
                    self._cv.wait()
                if self._stopping:
                    return
                target = self._requested
                prev = self._completed
            try:
                self._sync_fn()
            except BaseException as err:
                with self._cv:
                    self._error = err
                    self._cv.notify_all()
                return
            with self._cv:
                self._completed = max(self._completed, target)
                self._cv.notify_all()
            if hooks.enabled:
                hooks.metrics.counter(self._batches_metric).inc(target - prev)

    def stop(self, flush: bool) -> None:
        """Join the syncer.  ``flush=True`` (clean close: the owner has
        just run a final ``sync()``) marks all tickets complete;
        ``flush=False`` (crash) leaves them uncovered so waiters fail."""
        with self._cv:
            self._stopping = True
            if flush and self._error is None:
                self._completed = self._requested
            self._issue_ts.clear()
            self._cv.notify_all()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)


class FileWal:
    """Write(index, entry) / truncate(index) / sync + load_all replay.

    Layout: <dir>/segments/<first_index>.wal + <dir>/head containing the
    logical head index (entries below it are dead even if still on disk).
    """

    def __init__(self, path: str):
        self.path = path
        self.seg_dir = os.path.join(path, "segments")
        os.makedirs(self.seg_dir, exist_ok=True)
        self._head_path = os.path.join(path, "head")
        self._head_index = self._read_head()  # guarded-by: _lock
        self._entries = self._load_from_disk()  # guarded-by: _lock
        self._active = None  # guarded-by: _lock
        self._active_size = 0  # guarded-by: _lock
        self._needs_sync = False  # guarded-by: _lock
        # Segment rotation threshold.  Truncation can only unlink whole
        # dead segments, so disk reclamation is quantized to this size;
        # short soaks shrink it so steady-state disk usage sawtooths
        # instead of growing for the whole observation window.
        self.segment_target = _SEGMENT_TARGET
        # Fault-injection seam (chaos/live.py): called with no arguments
        # immediately before every fsync; raising OSError from it models a
        # failing disk.  None in production.
        self.fault_hook = None
        # Coarse mutex, like the reference simplewal's (simplewal.go:22-109):
        # the pooled processor runs persist and commit lanes concurrently.
        self._lock = threading.Lock()
        self._group = _GroupCommit(
            self.sync,
            name=f"storage-sync-wal-{os.path.basename(path) or 'wal'}",
            batches_metric="mirbft_wal_group_commit_batches",
            wait_metric="mirbft_wal_group_sync_wait_seconds",
        )

    # -- load ----------------------------------------------------------------

    def _read_head(self) -> int:
        try:
            with open(self._head_path, "rb") as f:
                return int(f.read().decode() or "0")
        except FileNotFoundError:
            return 0

    def _segments(self):
        names = []
        for name in os.listdir(self.seg_dir):
            if name.endswith(".wal"):
                names.append(int(name[:-4]))
        return sorted(names)

    def _load_from_disk(self):  # holds: _lock
        entries = []
        for first in self._segments():
            path = os.path.join(self.seg_dir, f"{first}.wal")
            with open(path, "rb") as f:
                data = f.read()
            pos = 0
            while pos < len(data):
                if pos + _REC_HEADER.size > len(data):
                    break  # torn tail
                length, index, crc = _REC_HEADER.unpack_from(data, pos)
                start = pos + _REC_HEADER.size
                payload = data[start : start + length]
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break  # torn/corrupt tail: discard the rest
                entries.append((index, pb.decode(pb.Persistent, payload)))
                pos = start + length
        return [(i, e) for i, e in entries if i >= self._head_index]

    def load_all(self, for_each) -> None:
        """Invoke for_each(index, pb.Persistent) over the live log.

        Snapshots under the lock, then calls back outside it: replay
        callbacks re-enter the stores (e.g. writing during recovery),
        and holding _lock across them would deadlock."""
        with self._lock:
            entries = list(self._entries)
        for index, entry in entries:
            for_each(index, entry)

    # -- runtime interface ---------------------------------------------------

    def _open_active(self, first_index: int):  # holds: _lock
        path = os.path.join(self.seg_dir, f"{first_index}.wal")
        created = not os.path.exists(path)
        self._active = open(path, "ab")
        self._active_size = self._active.tell()
        if created:
            _fsync_dir(self.seg_dir)

    def write(self, index: int, entry: pb.Persistent) -> None:
        with self._lock:
            self._write_locked(index, entry)

    def _write_locked(self, index: int, entry: pb.Persistent) -> None:  # holds: _lock
        if self._entries and index != self._entries[-1][0] + 1:
            raise CorruptWal(
                f"non-contiguous append: {index} after {self._entries[-1][0]}"
            )
        payload = pb.encode(entry)
        if self._active is None or self._active_size >= self.segment_target:
            if self._active is not None:
                self._active.flush()
                os.fsync(self._active.fileno())
                self._active.close()
            self._open_active(index)
        record = _REC_HEADER.pack(len(payload), index, zlib.crc32(payload))
        self._active.write(record + payload)
        self._active_size += len(record) + len(payload)
        self._entries.append((index, entry))
        self._needs_sync = True
        if hooks.enabled:
            hooks.metrics.counter("mirbft_wal_appends_total").inc()

    def truncate(self, index: int) -> None:
        """Truncate-front: drop every entry with index < the given index."""
        with self._lock:
            self._truncate_locked(index)

    def _truncate_locked(self, index: int) -> None:  # holds: _lock
        self._head_index = index
        with open(self._head_path + ".tmp", "wb") as f:
            f.write(str(index).encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(self._head_path + ".tmp", self._head_path)
        _fsync_dir(self.path)
        self._entries = [(i, e) for i, e in self._entries if i >= index]
        # Remove whole segments that ended below the head.
        segments = self._segments()
        unlinked = False
        for seg_first, seg_next in zip(segments, segments[1:]):
            if seg_next <= index:
                seg_path = os.path.join(self.seg_dir, f"{seg_first}.wal")
                if self._active is not None and self._active.name == seg_path:
                    continue
                os.unlink(seg_path)
                unlinked = True
        if unlinked:
            _fsync_dir(self.seg_dir)

    def sync(self) -> None:
        with self._lock:
            if self._active is not None and self._needs_sync:
                if self.fault_hook is not None:
                    self.fault_hook()
                start = time.perf_counter() if hooks.enabled else 0.0
                self._active.flush()
                os.fsync(self._active.fileno())
                self._needs_sync = False
                if hooks.enabled:
                    m = hooks.metrics
                    m.counter("mirbft_wal_fsyncs_total").inc()
                    m.histogram("mirbft_wal_fsync_seconds").observe(
                        time.perf_counter() - start
                    )

    def sync_token(self) -> int:
        """Group-commit: register a durability request covering everything
        written so far; redeem with ``wait(token)``."""
        return self._group.token()

    def wait(self, token: int, timeout: float | None = None) -> bool:
        return self._group.wait(token, timeout)

    def disk_bytes(self) -> int:
        """On-disk footprint (head file + segments); for the resource
        sampler's ``mirbft_resource_disk_bytes{store="wal"}`` series."""
        return _tree_bytes(self.path)

    def close(self) -> None:
        try:
            self.sync()
        except OSError:
            # Final fsync failed (e.g. an armed fault hook): tickets stay
            # uncovered so pending waiters fail instead of being lied to.
            self._group.stop(flush=False)
        else:
            self._group.stop(flush=True)
        with self._lock:
            if self._active is not None:
                self._active.close()
                self._active = None

    def crash(self) -> None:
        """Crash-kill teardown: release the file handle WITHOUT the
        close-time fsync, modeling power loss.  Unsynced appends may or
        may not survive — exactly the window the durable-prefix invariant
        must tolerate."""
        self._group.stop(flush=False)
        with self._lock:
            if self._active is not None:
                self._active.close()
                self._active = None


_REQ_HEADER = struct.Struct("<BII")  # op, ack_len, data_len
_OP_STORE = 1
_OP_COMMIT = 2

# Live compaction trigger for the request store's intent log: rewrite
# the live set once the log passes the size floor AND is mostly dead
# weight.  Without it the append-only log grows for the whole process
# lifetime (compaction only ran at open), which the resource-leak soak
# would rightly flag as disk growth.
_COMPACT_MIN_BYTES = 4 * 1024 * 1024
_COMPACT_DEAD_RATIO = 4


class FileRequestStore:
    """store/get/commit/sync + uncommitted replay.

    An intent log: STORE records carry (ack, data); COMMIT records carry the
    ack only.  The live (uncommitted) set is the stores minus the commits;
    compaction rewrites just the live set — at open, and live whenever
    the log exceeds ``compact_min_bytes`` while being mostly dead weight
    (so long-running processes reclaim disk instead of growing forever).
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._log_path = os.path.join(path, "requests.log")
        # key -> (ack, data, record_bytes); record_bytes feeds the live
        # size the compaction trigger compares the log against.
        self._index: dict[bytes, tuple] = {}  # guarded-by: _lock
        self._replay()
        self._compact()
        self._file = open(self._log_path, "ab")  # guarded-by: _lock
        self.compact_min_bytes = _COMPACT_MIN_BYTES
        self._log_size = self._file.tell()  # guarded-by: _lock
        self._live_size = self._log_size  # guarded-by: _lock
        # Pre-fsync fault seam, mirroring FileWal.fault_hook.
        self.fault_hook = None
        # store/commit run from different pooled lanes (reference reqstore
        # wraps BadgerDB, which is internally synchronized; our file log
        # needs the mutex).
        self._lock = threading.Lock()
        self._group = _GroupCommit(
            self.sync,
            name=f"storage-sync-reqstore-{os.path.basename(path) or 'reqs'}",
            batches_metric="mirbft_reqstore_group_commit_batches",
            wait_metric="mirbft_reqstore_group_sync_wait_seconds",
        )

    @staticmethod
    def _key(ack: pb.RequestAck) -> bytes:
        return (
            wire.encode_varint(ack.client_id)
            + wire.encode_varint(ack.req_no)
            + ack.digest
        )

    def _replay(self) -> None:  # holds: _lock
        try:
            with open(self._log_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        pos = 0
        while pos + _REQ_HEADER.size <= len(data):
            op, ack_len, data_len = _REQ_HEADER.unpack_from(data, pos)
            pos += _REQ_HEADER.size
            if pos + ack_len + data_len > len(data):
                break  # torn tail
            try:
                ack = pb.decode(pb.RequestAck, data[pos : pos + ack_len])
            except ValueError:
                break
            payload = data[pos + ack_len : pos + ack_len + data_len]
            pos += ack_len + data_len
            if op == _OP_STORE:
                self._index[self._key(ack)] = (
                    ack,
                    payload,
                    _REQ_HEADER.size + ack_len + data_len,
                )
            elif op == _OP_COMMIT:
                self._index.pop(self._key(ack), None)

    def _compact(self) -> None:  # holds: _lock
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as f:
            for ack, data, _size in self._index.values():
                self._write_record(f, _OP_STORE, ack, data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._log_path)
        _fsync_dir(self.path)

    def _maybe_compact_locked(self) -> None:  # holds: _lock
        if self._log_size < self.compact_min_bytes:
            return
        if self._log_size <= _COMPACT_DEAD_RATIO * max(self._live_size, 1):
            return
        self._file.flush()
        self._file.close()
        self._compact()
        self._file = open(self._log_path, "ab")
        self._log_size = self._file.tell()
        self._live_size = self._log_size
        if hooks.enabled:
            hooks.metrics.counter("mirbft_reqstore_compactions_total").inc()

    @staticmethod
    def _write_record(f, op: int, ack: pb.RequestAck, data: bytes) -> int:
        ack_bytes = pb.encode(ack)
        f.write(_REQ_HEADER.pack(op, len(ack_bytes), len(data)))
        f.write(ack_bytes)
        f.write(data)
        return _REQ_HEADER.size + len(ack_bytes) + len(data)

    # -- runtime interface ---------------------------------------------------

    def store(self, ack: pb.RequestAck, data: bytes) -> None:
        with self._lock:
            size = self._write_record(self._file, _OP_STORE, ack, data or b"")
            self._log_size += size
            key = self._key(ack)
            old = self._index.pop(key, None)
            if old is not None:
                self._live_size -= old[2]
            self._index[key] = (ack, data or b"", size)
            self._live_size += size
            if hooks.enabled:
                hooks.metrics.counter("mirbft_reqstore_appends_total").inc()

    def get(self, ack: pb.RequestAck) -> bytes | None:
        with self._lock:
            entry = self._index.get(self._key(ack))
        return entry[1] if entry is not None else None

    def commit(self, ack: pb.RequestAck) -> None:
        with self._lock:
            self._log_size += self._write_record(
                self._file, _OP_COMMIT, ack, b""
            )
            old = self._index.pop(self._key(ack), None)
            if old is not None:
                self._live_size -= old[2]
            self._maybe_compact_locked()

    def sync(self) -> None:
        with self._lock:
            if self.fault_hook is not None:
                self.fault_hook()
            start = time.perf_counter() if hooks.enabled else 0.0
            self._file.flush()
            os.fsync(self._file.fileno())
            if hooks.enabled:
                m = hooks.metrics
                m.counter("mirbft_reqstore_fsyncs_total").inc()
                m.histogram("mirbft_reqstore_fsync_seconds").observe(
                    time.perf_counter() - start
                )

    def uncommitted(self, for_each) -> None:
        """Invoke for_each(ack) for every stored-but-uncommitted request, in
        deterministic key order.

        Snapshots under the lock, then calls back outside it: replay
        callbacks re-enter the store (propose paths store/commit), and
        holding _lock across them would deadlock."""
        with self._lock:
            acks = [self._index[key][0] for key in sorted(self._index)]
        for ack in acks:
            for_each(ack)

    def pending_count(self) -> int:
        """Stored-but-uncommitted entries.  Duplicate stores overwrite in
        place, so under a duplication flood this is the memory-bound
        evidence the chaos audit reads: at most one pending entry per
        distinct request."""
        with self._lock:
            return len(self._index)

    def sync_token(self) -> int:
        """Group-commit ticket, mirroring FileWal.sync_token."""
        return self._group.token()

    def wait(self, token: int, timeout: float | None = None) -> bool:
        return self._group.wait(token, timeout)

    def disk_bytes(self) -> int:
        """On-disk footprint of the intent log; for the resource
        sampler's ``mirbft_resource_disk_bytes{store="reqstore"}``."""
        return _tree_bytes(self.path)

    def close(self) -> None:
        try:
            self.sync()
        except OSError:
            self._group.stop(flush=False)
        else:
            self._group.stop(flush=True)
        with self._lock:
            self._file.close()

    def crash(self) -> None:
        """Crash-kill teardown: release the handle without the orderly
        fsync (see FileWal.crash).  In-process simulation cannot drop the
        page cache, but the skipped fsync still distinguishes the crash
        path from clean shutdown for the durable-prefix audit."""
        self._group.stop(flush=False)
        with self._lock:
            self._file.close()


# -- snapshot staging (state transfer) ---------------------------------------
#
# The transfer engine (runtime/transfer.py) stages a verified snapshot to
# disk *before* adoption so a crash mid-install restarts cleanly: the core
# re-emits state_transfer on restart (TEntry > CEntry in the WAL), the
# engine finds the staged blob for the same target, and completes the
# install without re-fetching.  All fsync-bearing snapshot file I/O lives
# here (lint rules W10/W17).


def write_snapshot_file(path: str, blob: bytes) -> None:
    """Atomically persist a snapshot blob: tmp + fsync + rename + dir
    fsync, so ``path`` either holds the complete blob or does not exist —
    a torn staging file can never be mistaken for a verified snapshot."""
    directory = os.path.dirname(path) or "."
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(directory)


def read_snapshot_file(path: str) -> bytes | None:
    """Read a staged snapshot blob, or None when absent."""
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def remove_snapshot_file(path: str) -> None:
    """Discard a staged snapshot (post-install or on target change); the
    unlink is made durable so a crash cannot resurrect a consumed blob."""
    directory = os.path.dirname(path) or "."
    try:
        os.unlink(path)
    except OSError:
        return
    _fsync_dir(directory)


# -- application state (replicated app snapshots) -----------------------------
#
# The app commit stream (mirbft_tpu/app/stream.py) persists its state as
# ONE atomic blob: the applied consensus seq_no, the apply index, the
# journal chain, and the state machine's own snapshot travel together,
# so a crash at any instant leaves either the old complete state or the
# new complete state — never an applied-index that disagrees with the
# entries actually absorbed (the double-apply-after-restart bug class).
# All fsync-bearing app-state file I/O lives here (lint rules W10/W18).


def write_app_state(path: str, blob: bytes) -> None:
    """Atomically persist an app-state blob (tmp + fsync + rename + dir
    fsync): the applied-index inside the blob can never be observed
    without the state it describes."""
    write_snapshot_file(path, blob)


def read_app_state(path: str) -> bytes | None:
    """Read a persisted app-state blob, or None when absent."""
    return read_snapshot_file(path)


def remove_app_state(path: str) -> None:
    """Durably discard a persisted app-state blob."""
    remove_snapshot_file(path)
