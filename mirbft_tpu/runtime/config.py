"""Local, static node configuration (reference: config.go:13-61).

The consensus-replicated configuration (node set, f, buckets, checkpoint
interval) lives in pb.NetworkConfig and changes only via reconfiguration;
this is the per-node operational config.
"""

from __future__ import annotations

from dataclasses import dataclass

from .log import ConsoleLogger, Logger


@dataclass
class Config:
    id: int
    logger: Logger = None
    # Max requests per batch (batches may be cut smaller on heartbeats).
    batch_size: int = 1
    # Leader heartbeat period, in ticks.
    heartbeat_ticks: int = 2
    # Ticks without commit progress before suspecting the epoch.
    suspect_ticks: int = 4
    # Ticks to wait on a new-epoch leader; must be >= 2 (rebroadcast is
    # computed at half this value).
    new_epoch_timeout_ticks: int = 8
    # Per-remote-node byte budget for buffered not-yet-applyable messages.
    buffer_size: int = 5 * 1024 * 1024
    # Ingress frame bounds enforced by msgfilter.pre_process before a
    # peer message enters the serializer; raise max_batch_acks together
    # with batch_size when reconfiguring for larger batches.
    max_batch_acks: int = 256
    max_request_bytes: int = 1024 * 1024
    max_digest_bytes: int = 64
    # State-transfer ingress bounds (runtime/transfer.py): per-chunk
    # payload cap enforced on both the donor's chunking and the fetcher's
    # ingress (msgfilter.check_snapshot_chunk — a byzantine donor must
    # not be able to OOM a fetcher), and a total reassembled-snapshot
    # cap bounding chunk-count floods.
    max_snapshot_chunk_bytes: int = 256 * 1024
    max_snapshot_bytes: int = 64 * 1024 * 1024
    # Optional callable(state_event) invoked inside the serializer before
    # each event application (the tracing hook; see eventlog.Recorder).
    event_interceptor: object = None
    # HTTP observability endpoint (GET /metrics, /status, /healthz).
    # Off by default; set a port to serve (0 binds an ephemeral port,
    # read back via Node.metrics_address).  Exposition payloads come
    # from the obsv registry/status module — see obsv/exporter.py.
    metrics_port: int | None = None
    metrics_host: str = "127.0.0.1"
    # Action-executor selection for runtime embedders that build their
    # processor via runtime.build_processor (chaos/live.py, bench.py):
    # serial | pool | tpu | tpu-pool | pipelined | tpu-pipelined.
    processor: str = "serial"
    # Ack/quorum bookkeeping plane: "host" keeps the numpy _FastAcks
    # mirror; "device" routes ack frames through the dense jax bitmask
    # plane (core.device_tracker), falling back to host automatically
    # when no usable jax backend exists.  None defers to the
    # MIRBFT_ACK_PLANE env knob (default host).  docs/DEVICE_TRACKER.md.
    ack_plane: str | None = None
    # Device-plane frame coalescing: defer the ack kernel flush until at
    # least this many rows are queued (1 = flush every frame).  Sync
    # points (scalar mutation, tick boundaries, oracle audits) force an
    # earlier flush+drain, so raising it only trades materialization
    # latency for amortizing the pow2-padded kernel launch over many
    # small frames.  None defers to the MIRBFT_ACK_FLUSH_ROWS env knob
    # (default 1).  docs/DEVICE_TRACKER.md.
    ack_flush_rows: int | None = None
    # Divergence-oracle audit stride: install a shadow sampler auditing
    # every Nth ack frame (None leaves hooks.shadow to the embedder; the
    # MIRBFT_SHADOW_STRIDE env knob overrides the sampler default).
    # docs/OBSERVABILITY.md#shadow-oracle.
    shadow_stride: int | None = None
    # MAC-authenticated replica channels (docs/CRYPTO.md): when on, every
    # node/hello/transfer transport frame carries a per-link MAC tag
    # derived from auth_secret (crypto/mac.py) and bad-MAC frames are
    # rejected at ingress (mirbft_mac_rejections_total).  The client
    # propose lane stays signature-authenticated.  All members of a
    # cluster must agree on both knobs — a mixed cluster rejects the
    # unauthenticated minority's frames by design.
    link_auth: bool = False
    auth_secret: bytes = b""

    def __post_init__(self):
        if self.logger is None:
            self.logger = ConsoleLogger()
        if self.new_epoch_timeout_ticks < 2:
            raise ValueError("new_epoch_timeout_ticks must be >= 2")
        valid = ("serial", "pool", "tpu", "tpu-pool", "pipelined", "tpu-pipelined")
        if self.processor not in valid:
            raise ValueError(
                f"processor must be one of {valid}, got {self.processor!r}"
            )
        if self.ack_plane not in (None, "host", "device"):
            raise ValueError(
                f"ack_plane must be host|device, got {self.ack_plane!r}"
            )
        if self.shadow_stride is not None and self.shadow_stride < 1:
            raise ValueError("shadow_stride must be >= 1")
        if self.ack_flush_rows is not None and self.ack_flush_rows < 1:
            raise ValueError("ack_flush_rows must be >= 1")
        if self.max_snapshot_chunk_bytes < 1:
            raise ValueError("max_snapshot_chunk_bytes must be >= 1")
        if self.max_snapshot_bytes < self.max_snapshot_chunk_bytes:
            raise ValueError(
                "max_snapshot_bytes must be >= max_snapshot_chunk_bytes"
            )
        if self.link_auth and not self.auth_secret:
            raise ValueError("link_auth requires a non-empty auth_secret")
